"""Wire-level (cross-process) graftlint checks.

The intra-process checks (lock-order, resource-lifecycle, …) guard one
process's invariants; this module guards the invariants BETWEEN
processes, built from the same :class:`TreeIndex` facts:

``rpc-cycle``
    Builds the cross-process request-reply graph: every synchronous
    send site (``.call`` round-trips and framed send-then-wait
    requests) resolved to the handler ladder that dispatches its op,
    attributed to the *process class* on each side (the Python class
    containing the send / the ladder — Head, Node, RemoteHead,
    WorkerRuntime, ObjectServer, _ClientSession…).  Two finding shapes:

    - a strongly-connected component of ≥2 process classes in the
      synchronous-request graph (A waits on B while B waits on A —
      the distributed deadlock shape), and
    - a handler that, through the intra-class call graph, reaches a
      synchronous send toward a class that sends ops this very ladder
      dispatches — a reverse RPC toward the requesting class.  If the
      requester issues its call from the thread that serves OUR
      reverse request, both sides park forever.  Deliberate designs
      (handlers hopped onto their own thread before blocking) are
      baselined with a justification.

``reply-completeness``
    Every request-reply handler (a function binding the wire framing's
    ``req_id``) must pass the id onward on EVERY path — reply, fail
    the parked slot, or delegate — including exception paths.  A path
    that drops the id leaves the requester parked for its full
    timeout: the exact shape behind the 2.0 s → 10 ms teardown fixes.

``death-path-completeness``
    Every registry of parked waiters (pending reply slots, stream-sub
    slots, arg leases, pool checkouts) must have a removal site
    reachable from a death/disconnect handler (``remove_node``,
    worker-death, channel-EOF, ``fail_all`` families) or a teardown
    method via the intra-class call graph.  A registry only ever
    cleaned on the happy path wedges its waiters when the peer dies —
    the FT-readiness guarantee the restartable-head work builds on.
"""

from __future__ import annotations

import re
from collections import defaultdict, deque
from typing import Dict, List, Optional, Set, Tuple

from .analysis import (
    DEATH_METHOD_RE,
    REGISTRY_NAME_RE,
    TEARDOWN_METHOD_NAMES,
    ModuleInfo,
    SendSite,
    TreeIndex,
)
from .checks import Finding, _CallGraph, _find_cycles

CHECK_RPC_CYCLE = "rpc-cycle"
CHECK_REPLY = "reply-completeness"
CHECK_DEATH_PATH = "death-path-completeness"

# reply/ack tags are the *response* half of a round-trip, not requests;
# they never create request edges even when sent from a waiting function
_REPLY_OP_RE = re.compile(r"(rep$|^pong$|^ack$)")


# --------------------------------------------------------------- proc graph


class _ProcGraph:
    """Cross-process request-reply facts extracted once per tree."""

    def __init__(self, idx: TreeIndex):
        self.idx = idx
        # op -> [(class key, chain, path)]; class keys are bare class
        # names (unique per tree in this codebase; collisions merge)
        self.op_handlers: Dict[str, List[Tuple[str, object, str]]] = \
            defaultdict(list)
        # op -> [(class key, path, SendSite)]  (synchronous sends only)
        self.op_senders: Dict[str, List[Tuple[str, str, SendSite]]] = \
            defaultdict(list)
        # path -> func qualname -> its synchronous non-reply send sites
        self.sync_sends_by_func: Dict[str, Dict[str, List[SendSite]]] = {}
        self._cgs: Dict[str, _CallGraph] = {}
        self._collect()

    def callgraph(self, path: str) -> _CallGraph:
        cg = self._cgs.get(path)
        if cg is None:
            cg = self._cgs[path] = _CallGraph(self.idx.modules[path])
        return cg

    @staticmethod
    def _cls_of(qual: Optional[str], mod: ModuleInfo,
                path: str) -> Optional[str]:
        if qual is None:
            return None
        fi = mod.functions.get(qual)
        if fi is not None and fi.cls:
            return fi.cls
        if "." in qual:
            head = qual.split(".", 1)[0]
            if head in mod.classes:
                return head
        return f"<module {path}>"

    def _collect(self) -> None:
        for path, mod in self.idx.modules.items():
            waiting_funcs = {
                q for q, fi in mod.functions.items()
                if any(b.kind in ("wait", "result") for b in fi.blocking)}
            for chain in mod.handlers:
                cls = self._cls_of(chain.func, mod, path)
                if cls is None:
                    continue
                for op, _line in chain.ops:
                    self.op_handlers[op].append((cls, chain, path))
            by_func: Dict[str, List[SendSite]] = defaultdict(list)
            self.sync_sends_by_func[path] = by_func
            for s in mod.sends:
                if s.prefix or s.func is None:
                    continue
                if _REPLY_OP_RE.search(s.op):
                    continue
                sync = s.sync or s.func in waiting_funcs
                if not sync:
                    continue
                by_func[s.func].append(s)
                cls = self._cls_of(s.func, mod, path)
                if cls is None:
                    continue
                self.op_senders[s.op].append((cls, path, s))

    def sync_edges(self):
        """(sender_cls, handler_cls, op, path, SendSite) for every
        synchronous cross-class request."""
        for op, senders in sorted(self.op_senders.items()):
            handlers = self.op_handlers.get(op, ())
            for scls, spath, site in senders:
                for hcls, _chain, hpath in handlers:
                    if hcls != scls:
                        yield scls, hcls, op, spath, site, hpath


# ------------------------------------------------------------- rpc-cycle


def check_rpc_cycle(idx: TreeIndex) -> List[Finding]:
    pg = _ProcGraph(idx)
    findings: List[Finding] = []

    # ---- shape 1: synchronous request cycles between process classes
    graph: Dict[str, Set[str]] = defaultdict(set)
    rep: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for scls, hcls, op, spath, site, _hpath in pg.sync_edges():
        graph[scls].add(hcls)
        rep.setdefault((scls, hcls), (spath, site.line, op))
    for cycle in _find_cycles(graph):
        edges = []
        for i, node in enumerate(cycle):
            nxt = cycle[(i + 1) % len(cycle)]
            r = rep.get((node, nxt))
            if r:
                edges.append(f"{node} --{r[2]}--> {nxt} "
                             f"(sent at {r[0]}:{r[1]})")
        first = rep.get((cycle[0], cycle[1 % len(cycle)]),
                        ("<unknown>", 0, ""))
        findings.append(Finding(
            check=CHECK_RPC_CYCLE, path=first[0], line=first[1],
            context="-", detail="cycle:" + "<->".join(cycle),
            message=("synchronous request-reply cycle between process "
                     "classes " + " -> ".join(cycle + [cycle[0]]) + "; "
                     + "; ".join(edges) + " — if each side issues its "
                     "request from the thread that serves the other's, "
                     "both park forever")))

    # ---- shape 2: handler blocks on a reverse RPC toward its requester
    seen: Set[str] = set()
    for op, handlers in sorted(pg.op_handlers.items()):
        senders = {scls for scls, _p, _s in pg.op_senders.get(op, ())}
        if not senders:
            continue
        for hcls, chain, hpath in handlers:
            mod = idx.modules[hpath]
            cg = pg.callgraph(hpath)
            # seed the closure from the op's OWN branch callees: walking
            # the whole ladder function would attribute another branch's
            # sends to this op.  A branch with no resolvable self-method
            # callees is self-contained — its direct sends are either
            # replies (excluded) or reported via their own class edge.
            roots = []
            for callee in chain.op_calls.get(op, ()):
                qual = f"{hcls}.{callee}"
                if qual in mod.functions:
                    roots.append(qual)
            if not roots:
                continue
            hit = None
            for path_quals, send in _closure_sync_sends(
                    pg, hpath, cg, hcls, roots):
                targets = {tcls for tcls, _c, _p in
                           pg.op_handlers.get(send.op, ())}
                back = sorted((targets & senders) - {hcls})
                if back:
                    hit = (path_quals, send, back)
                    break
            if hit is None:
                continue
            path_quals, send, back = hit
            # one finding per (ladder, reverse op): the per-op variants
            # share the same blocking closure and the same fix
            key = f"{hcls}:{chain.func}->{send.op}"
            if key in seen:
                continue
            seen.add(key)
            # cite the requesting class's own send site, not whichever
            # class happened to send the op first
            sender_at = next(s for s in pg.op_senders[op]
                             if s[0] == back[0])
            findings.append(Finding(
                check=CHECK_RPC_CYCLE, path=hpath, line=send.line,
                context=chain.func,
                detail=f"reverse:{chain.func}->{send.op}",
                message=(f"handler ladder {chain.func} (op {op!r}, sent "
                         f"by {back[0]} at {sender_at[1]}:"
                         f"{sender_at[2].line}) reaches a synchronous "
                         f"send of {send.op!r} back toward {back[0]} "
                         f"via {' -> '.join(path_quals)} "
                         f"({hpath}:{send.line}) — the handler blocks "
                         "on a reverse RPC toward the requesting class; "
                         "serve it off-thread or make the reverse send "
                         "asynchronous")))
    return findings


def _closure_sync_sends(pg: _ProcGraph, path: str, cg: _CallGraph,
                        cls: str, roots: List[str]):
    """BFS the intra-class call graph from the handler roots, yielding
    (qual_path, SendSite) for every reachable synchronous send site in
    shortest-path order."""
    sends_by_func = pg.sync_sends_by_func.get(path, {})
    seen = set(roots)
    queue = deque([(r, [r]) for r in roots])
    while queue:
        cur, qpath = queue.popleft()
        for s in sends_by_func.get(cur, ()):
            yield qpath, s
        for tgt in cg.callees(cur):
            if tgt not in seen and tgt.startswith(f"{cls}."):
                seen.add(tgt)
                queue.append((tgt, qpath + [tgt]))


# ------------------------------------------------------ reply-completeness


_GAP_KINDS = {
    "fall": ("falls off the end", "the requester waits out its full "
             "timeout"),
    "return": ("returns early", "the requester waits out its full "
               "timeout"),
    "except": ("can raise out of the handler", "an exception path "
               "strands the parked waiter"),
}


def check_reply_completeness(idx: TreeIndex) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        # only modules participating in the wire protocol: a handler
        # ladder or send sites (serve-layer request_ids etc. are not
        # wire reply obligations)
        if not mod.handlers and not mod.sends:
            continue
        for qual, fi in sorted(mod.functions.items()):
            info = fi.reply
            if info is None or not info.gaps:
                continue
            if info.nested_delegate:
                continue  # deferred reply from a spawned thread
            if not info.sites:
                continue  # binds the id but never replies: plumbing
            for line, kind in info.gaps:
                what, why = _GAP_KINDS[kind]
                findings.append(Finding(
                    check=CHECK_REPLY, path=path, line=line,
                    context=qual, detail=f"{kind}:{qual}",
                    message=(f"request-reply handler {qual} {what} "
                             f"without replying (req id "
                             f"{info.param!r}) — {why}; reply, fail "
                             "the parked slot, or delegate on every "
                             "path (replies seen at line(s) "
                             f"{', '.join(map(str, info.sites[:4]))})")))
    return findings


# ------------------------------------------- death-path-completeness


def check_death_path_completeness(idx: TreeIndex) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        cg = _CallGraph(mod)
        # a name-matched (but not waiter-constructing) registry is only
        # a wire obligation in modules that actually speak the wire
        # protocol — driver-side "pending work" maps (data operators,
        # RL in-flight rollouts) surface failures as exceptions from
        # get/wait, not via a peer-death event
        has_wire = bool(mod.handlers or mod.sends)
        for cls, methods in sorted(mod.classes.items()):
            # registries inserted into by this class's methods
            stores: Dict[str, Tuple[str, object]] = {}
            clears: Dict[str, List[str]] = defaultdict(list)
            for qual, fi in mod.functions.items():
                if fi.cls != cls:
                    continue
                for st in fi.registry_stores:
                    if st.waiterish or (has_wire
                                        and REGISTRY_NAME_RE.search(st.attr)):
                        stores.setdefault(st.attr, (qual, st))
                for cl in fi.registry_clears:
                    # constructing the empty registry in __init__ is
                    # initialization, not cleanup
                    if cl.method == "reassign" and fi.name == "__init__":
                        continue
                    clears[cl.attr].append(qual)
            if not stores:
                continue
            # methods a death/disconnect event reaches (intra-class)
            death_roots = [
                f"{cls}.{m}" for m in mod.classes.get(cls, ())
                if DEATH_METHOD_RE.search(m) or m in TEARDOWN_METHOD_NAMES]
            reach: Set[str] = set(death_roots)
            queue = deque(death_roots)
            while queue:
                cur = queue.popleft()
                for tgt in cg.callees(cur):
                    if tgt not in reach and tgt.startswith(f"{cls}."):
                        reach.add(tgt)
                        queue.append(tgt)
            for attr, (qual, st) in sorted(stores.items()):
                cleaners = clears.get(attr, ())
                if not cleaners:
                    findings.append(Finding(
                        check=CHECK_DEATH_PATH, path=path, line=st.line,
                        context=cls, detail=f"never-cleared:{attr}",
                        message=(f"{cls}.{attr} registers parked "
                                 f"waiters (inserted in {qual}) but no "
                                 "method of the class ever removes or "
                                 "fails entries — every waiter leaks")))
                    continue
                # covered when some cleaner is itself a death/teardown
                # method, is reachable from one, or is a nested function
                # (a resident drainer thread owns the registry and pops
                # entries as completions/errors arrive)
                covered = any(
                    c in reach
                    or DEATH_METHOD_RE.search(c.split(".")[-1])
                    or c.split(".")[-1] in TEARDOWN_METHOD_NAMES
                    or c.count(".") >= 2
                    for c in cleaners)
                if not covered:
                    rel = ", ".join(sorted(set(cleaners))[:4])
                    findings.append(Finding(
                        check=CHECK_DEATH_PATH, path=path, line=st.line,
                        context=cls, detail=f"no-death-path:{attr}",
                        message=(f"{cls}.{attr} registers parked waiters "
                                 f"(inserted in {qual}) and is cleaned "
                                 f"only by {rel}, none of which is a "
                                 "death/disconnect or teardown handler "
                                 "or reachable from one "
                                 "(remove_node/worker-death/channel-EOF "
                                 "families) — when the peer dies, "
                                 "parked waiters wait out their full "
                                 "timeout instead of failing fast")))
    return findings
