"""The graftlint checks.  Each consumes the shared :class:`TreeIndex`.

Check ids are stable API: they appear in suppression comments, baseline
keys, and docs.  Never rename one; add a new id instead.
"""

from __future__ import annotations

import hashlib
import re
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .analysis import (
    RESOURCE_RELEASERS,
    TEARDOWN_METHOD_NAMES,
    FunctionInfo,
    ModuleInfo,
    TreeIndex,
)

CHECK_LOCK_ORDER = "lock-order"
CHECK_BLOCKING = "blocking-under-lock"
CHECK_GC = "gc-reentrancy"
CHECK_PROTOCOL = "protocol-completeness"
CHECK_PROTOCOL_VERSION = "protocol-version"
CHECK_CONFIG = "config-hygiene"
CHECK_METRICS = "metrics-hygiene"
CHECK_RESOURCE = "resource-lifecycle"
CHECK_THREAD_HYGIENE = "thread-hygiene"
CHECK_RING = "ring-protocol"
CHECK_RPC_CYCLE = "rpc-cycle"
CHECK_REPLY = "reply-completeness"
CHECK_DEATH_PATH = "death-path-completeness"
CHECK_RING_NET = "ring-protocol-net"
CHECK_DOC_SYNC = "doc-sync"

ALL_CHECKS = (
    CHECK_LOCK_ORDER,
    CHECK_BLOCKING,
    CHECK_GC,
    CHECK_PROTOCOL,
    CHECK_PROTOCOL_VERSION,
    CHECK_CONFIG,
    CHECK_METRICS,
    CHECK_RESOURCE,
    CHECK_THREAD_HYGIENE,
    CHECK_RING,
    CHECK_RPC_CYCLE,
    CHECK_REPLY,
    CHECK_DEATH_PATH,
    CHECK_RING_NET,
    CHECK_DOC_SYNC,
)

# Blocking kinds that also count as "channel send" for gc-reentrancy.
GC_BLOCKING_KINDS = {"send", "rpc", "recv"}


@dataclass(frozen=True)
class Finding:
    check: str
    path: str       # relative to the scanned root
    line: int
    message: str
    context: str    # enclosing function qualname (or "-")
    detail: str     # short symbolic token for the baseline key

    @property
    def key(self) -> str:
        """Line-number-independent identity used by baseline/suppression
        bookkeeping — survives unrelated edits to the same file."""
        return f"{self.check}:{self.path}:{self.context}:{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.check}] {self.message}")


# ------------------------------------------------------------- call graph


class _CallGraph:
    """Per-module intraprocedural call graph with transitive closures for
    'locks this function may acquire' and 'ways it may block'."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self._resolved: Dict[str, List[str]] = {}
        for qual, fi in mod.functions.items():
            targets = []
            for cs in fi.calls:
                tgt = self._resolve(fi, cs.callee, cs.is_self)
                if tgt is not None:
                    targets.append(tgt)
            self._resolved[qual] = targets
        self.acq_star = self._closure(
            {q: {a.lock for a in fi.acquires}
             for q, fi in mod.functions.items()})
        self.blk_star = self._closure(
            {q: {(b.kind, b.desc) for b in fi.blocking}
             for q, fi in mod.functions.items()})

    def _resolve(self, fi: FunctionInfo, callee: str,
                 is_self: bool) -> Optional[str]:
        if is_self and fi.cls is not None:
            qual = f"{fi.cls}.{callee}"
            if qual in self.mod.functions:
                return qual
            return None
        if callee in self.mod.functions:
            return callee
        return None

    def callees(self, qual: str) -> List[str]:
        return self._resolved.get(qual, [])

    def _closure(self, direct: Dict[str, set]) -> Dict[str, set]:
        out = {q: set(v) for q, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for q in out:
                for tgt in self._resolved.get(q, ()):
                    extra = out.get(tgt, set()) - out[q]
                    if extra:
                        out[q] |= extra
                        changed = True
        return out

    def first_blocking_path(self, root: str) -> Optional[Tuple[List[str], Tuple[str, str]]]:
        """BFS from ``root``: shortest call path to any blocking site or
        lock acquire.  Returns (path_of_quals, (kind, desc))."""
        seen = {root}
        queue = deque([(root, [root])])
        while queue:
            cur, path = queue.popleft()
            fi = self.mod.functions.get(cur)
            if fi is None:
                continue
            if fi.acquires:
                a = fi.acquires[0]
                return path, ("lock-acquire", a.lock)
            hazards = [b for b in fi.blocking if b.kind in GC_BLOCKING_KINDS]
            if hazards:
                return path, (hazards[0].kind, hazards[0].desc)
            for tgt in self.callees(cur):
                if tgt not in seen:
                    seen.add(tgt)
                    queue.append((tgt, path + [tgt]))
        return None


# ---------------------------------------------------------------- lock-order


def check_lock_order(idx: TreeIndex) -> List[Finding]:
    # edge (outer -> inner) -> representative (path, line, via)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for path, mod in idx.modules.items():
        cg = _CallGraph(mod)
        for qual, fi in mod.functions.items():
            for acq in fi.acquires:
                for outer in acq.held:
                    if outer != acq.lock:
                        edges.setdefault((outer, acq.lock),
                                         (path, acq.line, qual))
            for cs in fi.calls:
                if not cs.held:
                    continue
                tgt = cg._resolve(fi, cs.callee, cs.is_self)
                if tgt is None:
                    continue
                for inner in cg.acq_star.get(tgt, ()):
                    for outer in cs.held:
                        if outer != inner:
                            edges.setdefault(
                                (outer, inner),
                                (path, cs.line, f"{qual} via {cs.callee}()"))
    # cycle detection over the lock graph
    graph: Dict[str, Set[str]] = defaultdict(set)
    for (a, b) in edges:
        graph[a].add(b)
    findings: List[Finding] = []
    for cycle in _find_cycles(graph):
        locs = []
        for i, node in enumerate(cycle):
            nxt = cycle[(i + 1) % len(cycle)]
            rep = edges.get((node, nxt))
            if rep:
                locs.append(f"{node}->{nxt} at {rep[0]}:{rep[1]} ({rep[2]})")
        first = edges.get((cycle[0], cycle[1 % len(cycle)]),
                          ("<unknown>", 0, ""))
        findings.append(Finding(
            check=CHECK_LOCK_ORDER, path=first[0], line=first[1],
            context=first[2].split(" via ")[0],
            detail="<->".join(cycle),
            message=("potential deadlock: lock acquisition cycle "
                     + " -> ".join(cycle + [cycle[0]])
                     + "; " + "; ".join(locs))))
    return findings


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Canonical elementary cycles via SCC; one cycle reported per SCC."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    all_nodes = set(graph) | {w for vs in graph.values() for w in vs}
    for v in sorted(all_nodes):
        if v not in index:
            strongconnect(v)
    return sccs


# ------------------------------------------------------- blocking-under-lock


def check_blocking_under_lock(idx: TreeIndex) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        cg = _CallGraph(mod)
        for qual, fi in mod.functions.items():
            seen_direct: Set[Tuple[str, int]] = set()
            for b in fi.blocking:
                if not b.held:
                    continue
                if (b.desc, b.line) in seen_direct:
                    continue
                seen_direct.add((b.desc, b.line))
                findings.append(Finding(
                    check=CHECK_BLOCKING, path=path, line=b.line,
                    context=qual, detail=f"{b.desc}@{b.kind}",
                    message=(f"{b.desc}() ({b.kind}) called while holding "
                             f"{', '.join(b.held)}")))
            seen_calls: Set[Tuple[str, str]] = set()
            for cs in fi.calls:
                if not cs.held:
                    continue
                tgt = cg._resolve(fi, cs.callee, cs.is_self)
                if tgt is None or tgt == qual:
                    continue
                blocked = cg.blk_star.get(tgt, ())
                if not blocked:
                    continue
                key = (tgt, ",".join(cs.held))
                if key in seen_calls:
                    continue
                seen_calls.add(key)
                kinds = sorted({f"{d} ({k})" for k, d in blocked})
                findings.append(Finding(
                    check=CHECK_BLOCKING, path=path, line=cs.line,
                    context=qual, detail=f"call:{tgt}",
                    message=(f"calls {cs.callee}() while holding "
                             f"{', '.join(cs.held)}; it may block via "
                             + ", ".join(kinds[:3]))))
    return findings


# ----------------------------------------------------------- gc-reentrancy


def check_gc_reentrancy(idx: TreeIndex) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        cg = _CallGraph(mod)
        roots: List[Tuple[str, int, str]] = []  # (qual, line, why)
        for qual, fi in mod.functions.items():
            if fi.name == "__del__":
                roots.append((qual, fi.line, "__del__"))
            for cb_name, line in fi.weakref_callbacks:
                for cand in (f"{fi.cls}.{cb_name}" if fi.cls else None,
                             cb_name):
                    if cand and cand in mod.functions:
                        roots.append((cand, line,
                                      f"weakref callback ({qual})"))
                        break
        for qual, line, why in roots:
            hit = cg.first_blocking_path(qual)
            if hit is None:
                continue
            call_path, (kind, desc) = hit
            verb = ("acquires lock " + desc if kind == "lock-acquire"
                    else f"performs a channel round-trip via {desc} ({kind})")
            findings.append(Finding(
                check=CHECK_GC, path=path, line=line, context=qual,
                detail=f"{why}:{desc}",
                message=(f"{why} runs inside the garbage collector but its "
                         f"call graph ({' -> '.join(call_path)}) {verb}; "
                         "GC can fire on a thread already holding runtime "
                         "locks — defer to a reaper thread instead "
                         "(see ObjectRef._drop_queue)")))
    return findings


# ---------------------------------------------------- protocol completeness


def _gather_protocol(idx: TreeIndex):
    handled: Dict[str, List[Tuple[str, str, int]]] = defaultdict(list)
    chains: List[Tuple[str, "HandlerChain"]] = []  # noqa: F821
    sent: Dict[str, List[Tuple[str, int]]] = defaultdict(list)
    prefixes: Dict[str, List[Tuple[str, int]]] = defaultdict(list)
    # dispatcher functions: chains whose dispatch variable is an actual
    # parameter — a call `obj.kv("del", …)` with a literal in that slot
    # is a send even though no channel is visibly involved
    dispatchers: Dict[str, Set[int]] = defaultdict(set)
    for path, mod in idx.modules.items():
        for chain in mod.handlers:
            chains.append((path, chain))
            for op, line in chain.ops:
                handled[op].append((path, chain.func, line))
            fi = mod.functions.get(chain.func)
            if fi is not None and chain.param in fi.params:
                dispatchers[fi.name].add(fi.params.index(chain.param))
        for s in mod.sends:
            if s.prefix:
                prefixes[s.op].append((path, s.line))
            else:
                sent[s.op].append((path, s.line))
    dispatcher_sent: Set[str] = set()
    for path, mod in idx.modules.items():
        for leaf, lits, line in mod.lit_calls:
            for idx_ in dispatchers.get(leaf, ()):
                for argi, lit in lits:
                    if argi == idx_:
                        dispatcher_sent.add(lit)
    return handled, chains, sent, prefixes, dispatcher_sent


def check_protocol_completeness(idx: TreeIndex) -> List[Finding]:
    handled, chains, sent, prefixes, dispatcher_sent = _gather_protocol(idx)
    findings: List[Finding] = []
    for op, sites in sorted(sent.items()):
        if op in handled:
            continue
        path, line = sites[0]
        findings.append(Finding(
            check=CHECK_PROTOCOL, path=path, line=line, context="-",
            detail=f"unhandled:{op}",
            message=(f"op {op!r} is sent here but no handler chain "
                     "dispatches on it — a receiver will raise "
                     "'unknown op' at runtime")))
    for pfx, sites in sorted(prefixes.items()):
        if any(op.startswith(pfx) for op in handled):
            continue
        path, line = sites[0]
        findings.append(Finding(
            check=CHECK_PROTOCOL, path=path, line=line, context="-",
            detail=f"unhandled-prefix:{pfx}",
            message=(f"dynamic op prefix {pfx!r}* is sent here but no "
                     "handler dispatches on any matching op")))
    # dead handlers: only meaningful in real dispatch ladders (>= 3 ops)
    for path, chain in chains:
        if len(chain.ops) < 3:
            continue
        for op, line in chain.ops:
            if op in sent or op in dispatcher_sent:
                continue
            if any(op.startswith(p) for p in prefixes):
                continue
            findings.append(Finding(
                check=CHECK_PROTOCOL, path=path, line=line,
                context=chain.func, detail=f"dead:{op}",
                message=(f"handler for op {op!r} in {chain.func} has no "
                         "send site anywhere in the tree — dead wire "
                         "code or a sender the analyzer cannot see")))
    return findings


def protocol_ops_hash(idx: TreeIndex) -> Tuple[str, Optional[int]]:
    """Stable digest of the wire-op surface + current PROTOCOL_VERSION."""
    handled, _chains, sent, prefixes, _disp = _gather_protocol(idx)
    ops = sorted(set(handled) | set(sent) | {p + "*" for p in prefixes})
    digest = hashlib.sha256("\n".join(ops).encode()).hexdigest()[:16]
    version = None
    for mod in idx.modules.values():
        if mod.protocol_version is not None:
            version = (mod.protocol_version if version is None
                       else max(version, mod.protocol_version))
    return digest, version


def check_protocol_version(idx: TreeIndex,
                           baseline_protocol: Optional[dict]) -> List[Finding]:
    digest, version = protocol_ops_hash(idx)
    if not baseline_protocol:
        return []
    base_hash = baseline_protocol.get("ops_hash")
    base_version = baseline_protocol.get("version")
    if digest == base_hash:
        return []
    where, line = "<tree>", 0
    for path, mod in idx.modules.items():
        if mod.protocol_version is not None:
            where, line = path, 1
            break
    if version == base_version:
        msg = (f"wire-op set changed (hash {base_hash} -> {digest}) but "
               f"PROTOCOL_VERSION is still {version}: bump it in "
               "core/protocol.py, then refresh the baseline with "
               "--update-baseline")
    else:
        msg = (f"wire-op set changed (hash {base_hash} -> {digest}) and "
               f"PROTOCOL_VERSION moved {base_version} -> {version}: "
               "refresh the recorded op-set baseline with --update-baseline")
    return [Finding(check=CHECK_PROTOCOL_VERSION, path=where, line=line,
                    context="-", detail=f"ops-hash:{digest}", message=msg)]


# ------------------------------------------------------------ config-hygiene


def check_config_hygiene(idx: TreeIndex) -> List[Finding]:
    config_paths: Set[str] = set()
    field_vars: Set[str] = set()
    bootstrap_vars: Set[str] = set()
    for path, mod in idx.modules.items():
        if mod.config_fields or mod.bootstrap_env:
            config_paths.add(path)
        for f in mod.config_fields:
            field_vars.add(f"RAY_TPU_{f.upper()}")
        bootstrap_vars.update(mod.bootstrap_env)
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        if path in config_paths:
            continue
        for read in mod.env_reads:
            if read.var in bootstrap_vars:
                if idx.doc_text and read.var not in idx.doc_text:
                    findings.append(Finding(
                        check=CHECK_CONFIG, path=path, line=read.line,
                        context="-", detail=f"undocumented:{read.var}",
                        message=(f"{read.var} is declared in core/config.py "
                                 "but not mentioned anywhere under docs/ "
                                 "or README.md")))
                continue
            if read.var in field_vars:
                findings.append(Finding(
                    check=CHECK_CONFIG, path=path, line=read.line,
                    context="-", detail=f"bypass:{read.var}",
                    message=(f"{read.var} maps to a Config field but is "
                             "read directly from the environment here — "
                             "route it through global_config() so cluster-"
                             "wide config snapshots stay authoritative")))
                continue
            findings.append(Finding(
                check=CHECK_CONFIG, path=path, line=read.line,
                context="-", detail=f"undeclared:{read.var}",
                message=(f"{read.var} is read from the environment but "
                         "declared neither as a Config field nor in "
                         "BOOTSTRAP_ENV_VARS in core/config.py — every "
                         "knob must have one discoverable declaration")))
    return findings


# ----------------------------------------------------------- metrics-hygiene


def check_metrics_hygiene(idx: TreeIndex) -> List[Finding]:
    regs: Dict[str, List[Tuple[str, "MetricReg"]]] = defaultdict(list)  # noqa: F821
    for path, mod in idx.modules.items():
        for m in mod.metrics:
            regs[m.name].append((path, m))
    findings: List[Finding] = []
    for name, sites in sorted(regs.items()):
        if len(sites) < 2:
            continue
        first_path, first = sites[0]
        types = {m.mtype for _p, m in sites}
        tagsets = {m.tag_keys for _p, m in sites if m.tag_keys is not None}
        for path, m in sites[1:]:
            if len(types) > 1:
                msg = (f"metric {name!r} is registered with conflicting "
                       f"types ({', '.join(sorted(types))}); first "
                       f"registration at {first_path}:{first.line}")
                detail = f"type-conflict:{name}"
            elif len(tagsets) > 1:
                msg = (f"metric {name!r} is registered with inconsistent "
                       f"tag sets {sorted(tagsets)}; first registration "
                       f"at {first_path}:{first.line}")
                detail = f"tag-conflict:{name}"
            else:
                msg = (f"metric {name!r} is registered more than once "
                       f"(also at {first_path}:{first.line}); register "
                       "each name exactly once and share the instance")
                detail = f"duplicate:{name}"
            findings.append(Finding(
                check=CHECK_METRICS, path=path, line=m.line,
                context="-", detail=detail, message=msg))
    return findings


# ------------------------------------------------------------------ doc-sync

# A metric mention in prose: lowercase `ray_tpu_…` with nothing
# identifier-ish (or a path/module separator) immediately before it.
# The lowercase requirement excludes RAY_TPU_* env vars; the lookbehind
# excludes `ray_tpu/util/...` paths and `foo.ray_tpu_x` attribute spells;
# `ray_tpu://` URLs and `ray_tpu.util` module paths never match because
# the literal `ray_tpu_` (with the trailing underscore) never appears in
# them.  A token may end in `_` — that is a documented *family prefix*
# (`ray_tpu_train_*`, or a long name split across a line break).
_DOC_METRIC_TOKEN = re.compile(r"(?<![A-Za-z0-9_/.])ray_tpu_[a-z0-9_]+")

# Histogram registrations fan out to these series suffixes at export
# time, so docs legitimately reference `<base>_count` / `_sum` /
# `_bucket` names that have no registration site of their own.
_HIST_SUFFIXES = ("_count", "_sum", "_bucket")


def check_doc_sync(idx: TreeIndex) -> List[Finding]:
    """Docs and the metric/span registry must agree.

    Forward: every ``ray_tpu_*`` metric token in the scanned docs must
    resolve to a registered metric (exactly, as a documented family
    prefix ending in ``_``, or as a histogram export suffix).  Reverse:
    every registered metric/span name must be mentioned somewhere in the
    docs — the stale-doc detector that keeps newly registered names from
    shipping undocumented.  Skipped entirely when no docs were scanned
    (fixture trees run with ``doc_roots=[]``)."""
    if not idx.doc_files:
        return []
    regs: Dict[str, Tuple[str, str, int]] = {}  # name -> (mtype, path, line)
    for path in sorted(idx.modules):
        mod = idx.modules[path]
        for m in list(mod.metrics) + list(mod.dynamic_metrics):
            regs.setdefault(m.name, (m.mtype, path, m.line))
    metric_names = {n for n, (t, _p, _l) in regs.items() if t != "span"}
    hist_names = {n for n, (t, _p, _l) in regs.items() if t == "histogram"}
    findings: List[Finding] = []
    for doc_path in sorted(idx.doc_files):
        for lineno, line in enumerate(idx.doc_files[doc_path], 1):
            for tok in _DOC_METRIC_TOKEN.findall(line):
                if tok in metric_names:
                    continue
                if tok.endswith("_") and any(
                        n.startswith(tok) for n in metric_names):
                    continue
                if any(tok.endswith(s) and tok[:-len(s)] in hist_names
                       for s in _HIST_SUFFIXES):
                    continue
                findings.append(Finding(
                    check=CHECK_DOC_SYNC, path=doc_path, line=lineno,
                    context="-", detail=f"unknown-name:{tok}",
                    message=(f"docs reference metric {tok!r} but no such "
                             "metric is registered anywhere in the tree — "
                             "fix the stale doc name or register the "
                             "metric")))
    doc_text = idx.doc_text
    prefixes = {t for t in _DOC_METRIC_TOKEN.findall(doc_text)
                if t.endswith("_")}
    for name in sorted(regs):
        mtype, path, line = regs[name]
        if name in doc_text:
            continue
        if any(name.startswith(p) for p in prefixes):
            continue
        findings.append(Finding(
            check=CHECK_DOC_SYNC, path=path, line=line,
            context="-", detail=f"undocumented:{name}",
            message=(f"{mtype} {name!r} is registered here but never "
                     "mentioned in the docs — document it in "
                     "docs/observability.md (or the owning surface doc)")))
    return findings


# --------------------------------------------------------- resource-lifecycle


def _teardown_reachable(mod: ModuleInfo, cg: "_CallGraph",
                        cls: str) -> Set[str]:
    """Quals of methods reachable (transitively, intra-class) from any
    teardown-family method of ``cls`` — the set a self-attr resource's
    release must intersect."""
    roots = [f"{cls}.{m}" for m in mod.classes.get(cls, ())
             if m in TEARDOWN_METHOD_NAMES]
    seen: Set[str] = set(roots)
    queue = deque(roots)
    while queue:
        cur = queue.popleft()
        for tgt in cg.callees(cur):
            if tgt not in seen and tgt.startswith(f"{cls}."):
                seen.add(tgt)
                queue.append(tgt)
    return seen


def check_resource_lifecycle(idx: TreeIndex) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        cg = _CallGraph(mod)
        # ---- class-owned resources (self.<attr> = <ctor>(...)) --------
        for cls, methods in mod.classes.items():
            acquires: Dict[str, "ResourceAcquire"] = {}  # noqa: F821
            releases: Dict[str, List[Tuple[str, "ReleaseSite"]]] = \
                defaultdict(list)  # noqa: F821
            has_teardown = any(m in TEARDOWN_METHOD_NAMES for m in methods)
            for m in methods:
                fi = mod.functions.get(f"{cls}.{m}")
                if fi is None:
                    continue
                for acq in fi.resources:
                    if acq.target.startswith("self.") \
                            and not acq.with_managed:
                        acquires.setdefault(acq.target, acq)
                for rel in fi.releases:
                    if rel.target.startswith("self."):
                        releases[rel.target].append((fi.qualname, rel))
            if acquires:
                reach = _teardown_reachable(mod, cg, cls)
                for target, acq in sorted(acquires.items()):
                    ok_methods = RESOURCE_RELEASERS[acq.kind]
                    sites = [(q, r) for q, r in releases.get(target, ())
                             if r.method in ok_methods]
                    if not sites:
                        findings.append(Finding(
                            check=CHECK_RESOURCE, path=path, line=acq.line,
                            context=f"{cls}", detail=f"leak:{target}",
                            message=(f"{cls} acquires {acq.kind} "
                                     f"{target} ({acq.ctor}) but no method "
                                     f"ever calls {target}."
                                     f"{'/'.join(sorted(ok_methods))}() — "
                                     "the OS resource outlives the object "
                                     "on every path")))
                    elif reach and not any(q in reach for q, _r in sites):
                        rel_at = ", ".join(sorted({q for q, _r in sites}))
                        findings.append(Finding(
                            check=CHECK_RESOURCE, path=path, line=acq.line,
                            context=f"{cls}",
                            detail=f"shutdown-miss:{target}",
                            message=(f"{cls} releases {acq.kind} {target} "
                                     f"only in {rel_at}, which is not "
                                     "reachable from any of its "
                                     "shutdown/close/teardown methods — "
                                     "the teardown path leaks it")))
            # ---- unretained service resources ------------------------
            # a class that manages lifecycle (has a teardown method) must
            # hold on to threads/pools it spins up at construction: an
            # anonymous `Thread(...).start()` in __init__/start* can
            # never be joined by shutdown
            if has_teardown:
                for m in methods:
                    if not (m in ("__init__", "open", "connect")
                            or m.startswith(("start", "_start"))):
                        continue
                    fi = mod.functions.get(f"{cls}.{m}")
                    if fi is None:
                        continue
                    for acq in fi.resources:
                        if acq.target == "<anon>" and acq.kind in (
                                "thread", "pool"):
                            findings.append(Finding(
                                check=CHECK_RESOURCE, path=path,
                                line=acq.line, context=fi.qualname,
                                detail=f"unretained:{acq.ctor}@{fi.qualname}",
                                message=(f"{fi.qualname} starts a "
                                         f"{acq.kind} without retaining "
                                         "the handle; this class has a "
                                         "teardown method, which can "
                                         "therefore never join it — "
                                         "store it on self and join at "
                                         "shutdown")))
        # ---- function-local resources ---------------------------------
        for qual, fi in mod.functions.items():
            for acq in fi.resources:
                if acq.target in ("<anon>", "<escaped>") \
                        or acq.target.startswith("self.") \
                        or acq.with_managed or acq.escapes:
                    continue
                ok_methods = RESOURCE_RELEASERS[acq.kind]
                sites = [r for r in fi.releases
                         if r.target == acq.target
                         and r.method in ok_methods]
                if not sites:
                    if acq.kind == "thread" and acq.daemon:
                        continue  # local daemon worker: fire-and-forget
                    findings.append(Finding(
                        check=CHECK_RESOURCE, path=path, line=acq.line,
                        context=qual,
                        detail=f"local-leak:{acq.target}",
                        message=(f"local {acq.kind} {acq.target!r} "
                                 f"({acq.ctor}) is never "
                                 f"{'/'.join(sorted(ok_methods))}()d in "
                                 f"{qual} and does not escape — leaked "
                                 "on every path")))
                elif not any(r.in_finally for r in sites) \
                        and acq.kind != "thread":
                    findings.append(Finding(
                        check=CHECK_RESOURCE, path=path, line=acq.line,
                        context=qual,
                        detail=f"exception-path:{acq.target}",
                        message=(f"local {acq.kind} {acq.target!r} "
                                 f"({acq.ctor}) is released only on the "
                                 f"normal path in {qual}; an exception "
                                 "between acquire and release leaks it — "
                                 "use try/finally or a with block")))
    return findings


# ------------------------------------------------------------ thread-hygiene


def check_thread_hygiene(idx: TreeIndex) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in idx.modules.items():
        cg = _CallGraph(mod)
        # functions that UNCONDITIONALLY spawn a thread per call (a
        # conditional spawn is usually a started-once guard)
        direct_spawn: Dict[str, int] = {}
        for qual, fi in mod.functions.items():
            for acq in fi.resources:
                if acq.kind == "thread" and not acq.in_loop \
                        and not acq.in_branch and not acq.with_managed:
                    direct_spawn.setdefault(qual, acq.line)
        # transitive closure: f spawns if any callee spawns
        spawns: Set[str] = set(direct_spawn)
        changed = True
        while changed:
            changed = False
            for qual in mod.functions:
                if qual in spawns:
                    continue
                if any(t in spawns for t in cg.callees(qual)):
                    spawns.add(qual)
                    changed = True
        for qual, fi in mod.functions.items():
            # direct per-item spawn inside a non-paced loop body
            for acq in fi.resources:
                if acq.kind == "thread" and acq.in_loop \
                        and not acq.paced_loop:
                    findings.append(Finding(
                        check=CHECK_THREAD_HYGIENE, path=path,
                        line=acq.line, context=qual,
                        detail=f"spawn-in-loop:{qual}",
                        message=(f"{qual} creates a thread inside a loop "
                                 "— per-item thread spawns turn a hot "
                                 "path into ~100us of clone/teardown per "
                                 "item; use a resident worker or pool")))
            # loop-resident call into a function that always spawns
            seen: Set[str] = set()
            for cs in fi.loop_calls:
                tgt = cg._resolve(fi, cs.callee, cs.is_self)
                if tgt is None or tgt == qual or tgt in seen:
                    continue
                if tgt in spawns:
                    seen.add(tgt)
                    findings.append(Finding(
                        check=CHECK_THREAD_HYGIENE, path=path,
                        line=cs.line, context=qual,
                        detail=f"spawn-via:{tgt}",
                        message=(f"{qual} calls {cs.callee}() inside a "
                                 f"loop and {tgt} unconditionally spawns "
                                 "a thread — a per-item thread creation "
                                 "reachable from a hot path (the PR-7 "
                                 "3-threads-per-stream-item shape)")))
    return findings


# -------------------------------------------------------------- ring-protocol


def check_ring_protocol_model(idx: TreeIndex,
                              cache=None) -> List[Finding]:
    """Exhaustive model check of the ring-channel protocol spec.

    Runs only when the scanned tree contains the channel implementation
    the spec mirrors (fixture trees don't pay for it).  A violation
    means an interleaving of the modeled mmap writes breaks a protocol
    invariant — fix channel.py AND ring_model.py together; the
    conformance test in tests/test_static_analysis.py keeps them honest.
    The result depends only on the lint tool's own sources, so it is
    cached under the tool digest.
    """
    from .ring_check import CHANNEL_PATH, check_ring_protocol

    if CHANNEL_PATH not in idx.modules:
        return []
    results = cache.get_check_result(CHECK_RING) if cache else None
    if results is None:
        results = check_ring_protocol()
        if cache is not None:
            cache.put_check_result(CHECK_RING, results)
    findings: List[Finding] = []
    for res in results:
        for v in res.violations:
            findings.append(Finding(
                check=CHECK_RING, path=CHANNEL_PATH, line=1,
                context=f"n_slots={v.n_slots}",
                detail=f"{v.kind}:n{v.n_slots}",
                message=(f"ring protocol model check failed: {v.render()}"
                         " — an interleaving of the published protocol's "
                         "mmap writes violates this invariant")))
    return findings


# ---------------------------------------------------------- ring-protocol-net


def check_ring_protocol_net_model(idx: TreeIndex,
                                  cache=None) -> List[Finding]:
    """Exhaustive model check of the NETWORK ring-channel protocol spec
    (``ring_model_net.py``): the cross-host transport contract, checked
    under doorbell loss/duplication/reorder and peer crash-restart.

    Runs only when the scanned tree contains the channel implementation
    (fixture trees don't pay for it).  The spec has no implementation
    yet — it is the machine-checked contract the cross-host transport
    PR implements against; a violation means the CONTRACT is broken
    and the port must not proceed."""
    from .ring_check import CHANNEL_PATH
    from .ring_model_net import check_net_ring_protocol

    if CHANNEL_PATH not in idx.modules:
        return []
    results = cache.get_check_result(CHECK_RING_NET) if cache else None
    if results is None:
        results = check_net_ring_protocol()
        if cache is not None:
            cache.put_check_result(CHECK_RING_NET, results)
    findings: List[Finding] = []
    for res in results:
        for v in res.violations:
            findings.append(Finding(
                check=CHECK_RING_NET, path=CHANNEL_PATH, line=1,
                context=f"n_slots={v.n_slots},crash={res.crash or '-'}",
                detail=f"{v.kind}:n{v.n_slots}:{res.crash or '-'}",
                message=(f"network ring protocol model check failed: "
                         f"{v.render()} — an interleaving of sends, "
                         "deliveries, faults and restarts violates "
                         "this invariant of the cross-host transport "
                         "contract")))
    return findings


# ------------------------------------------------------------------- driver


def run_checks(idx: TreeIndex,
               baseline_protocol: Optional[dict] = None,
               checks: Optional[Iterable[str]] = None,
               cache=None) -> List[Finding]:
    wanted = set(checks) if checks else set(ALL_CHECKS)
    findings: List[Finding] = []
    if CHECK_LOCK_ORDER in wanted:
        findings += check_lock_order(idx)
    if CHECK_BLOCKING in wanted:
        findings += check_blocking_under_lock(idx)
    if CHECK_GC in wanted:
        findings += check_gc_reentrancy(idx)
    if CHECK_PROTOCOL in wanted:
        findings += check_protocol_completeness(idx)
    if CHECK_PROTOCOL_VERSION in wanted:
        findings += check_protocol_version(idx, baseline_protocol)
    if CHECK_CONFIG in wanted:
        findings += check_config_hygiene(idx)
    if CHECK_METRICS in wanted:
        findings += check_metrics_hygiene(idx)
    if CHECK_DOC_SYNC in wanted:
        findings += check_doc_sync(idx)
    if CHECK_RESOURCE in wanted:
        findings += check_resource_lifecycle(idx)
    if CHECK_THREAD_HYGIENE in wanted:
        findings += check_thread_hygiene(idx)
    if CHECK_RING in wanted:
        findings += check_ring_protocol_model(idx, cache=cache)
    if wanted & {CHECK_RPC_CYCLE, CHECK_REPLY, CHECK_DEATH_PATH}:
        from .wire_checks import (
            check_death_path_completeness,
            check_reply_completeness,
            check_rpc_cycle,
        )

        if CHECK_RPC_CYCLE in wanted:
            findings += check_rpc_cycle(idx)
        if CHECK_REPLY in wanted:
            findings += check_reply_completeness(idx)
        if CHECK_DEATH_PATH in wanted:
            findings += check_death_path_completeness(idx)
    if CHECK_RING_NET in wanted:
        findings += check_ring_protocol_net_model(idx, cache=cache)
    findings = [f for f in findings
                if not idx.suppressed(f.path, f.line, f.check)]
    return sorted(findings, key=lambda f: (f.path, f.line, f.check, f.detail))
