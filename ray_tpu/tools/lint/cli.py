"""graftlint CLI: ``python -m ray_tpu.tools.lint`` (or ``python -m
ray_tpu lint``).

Exit codes: 0 clean (all findings baselined/suppressed), 1 unbaselined
findings, 2 usage or parse failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

from .analysis import collect_tree
from .baseline import Baseline, default_baseline_path
from .checks import ALL_CHECKS, Finding, protocol_ops_hash, run_checks


def default_root() -> str:
    """The installed ray_tpu package directory."""
    here = os.path.dirname(os.path.abspath(__file__))   # .../ray_tpu/tools/lint
    return os.path.dirname(os.path.dirname(here))


def default_doc_roots(root: str) -> List[str]:
    repo = os.path.dirname(root)
    out = []
    for cand in (os.path.join(repo, "docs"),
                 os.path.join(repo, "README.md")):
        if os.path.exists(cand):
            out.append(cand)
    return out


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    unbaselined: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline_keys: List[str] = field(default_factory=list)
    parse_errors: List = field(default_factory=list)
    ops_hash: str = ""
    protocol_version: Optional[int] = None
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.unbaselined and not self.parse_errors


def run_lint(root: Optional[str] = None,
             baseline_path: Optional[str] = None,
             doc_roots: Optional[List[str]] = None,
             checks: Optional[List[str]] = None,
             update_baseline: bool = False,
             use_baseline: bool = True) -> LintReport:
    """Programmatic entry point (the tier-1 test calls this)."""
    t0 = time.monotonic()
    root = root or default_root()
    if use_baseline and baseline_path is None:
        baseline_path = default_baseline_path()
    if doc_roots is None:
        doc_roots = default_doc_roots(root)
    idx = collect_tree(root, doc_roots=doc_roots)
    baseline = Baseline.load(baseline_path if use_baseline else None)
    findings = run_checks(idx, baseline_protocol=baseline.protocol,
                          checks=checks)
    digest, version = protocol_ops_hash(idx)
    if update_baseline:
        baseline.absorb(findings,
                        {"version": version, "ops_hash": digest},
                        ran_checks=checks)
        baseline.path = baseline.path or default_baseline_path()
        baseline.save()
        unbaselined, baselined, stale = [], findings, []
    else:
        unbaselined, baselined, stale = baseline.split(findings)
        if checks:
            # a filtered run cannot judge entries for checks it didn't run
            wanted = set(checks)
            stale = [k for k in stale if k.split(":", 1)[0] in wanted]
    return LintReport(findings=findings, unbaselined=unbaselined,
                      baselined=baselined, stale_baseline_keys=stale,
                      parse_errors=idx.parse_errors,
                      ops_hash=digest, protocol_version=version,
                      duration_s=time.monotonic() - t0)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.lint",
        description=("graftlint: concurrency- and protocol-invariant "
                     "static analyzer for the ray_tpu runtime"))
    p.add_argument("--root", default=None,
                   help="tree to scan (default: the ray_tpu package)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: the checked-in "
                        "ray_tpu/tools/lint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current findings and "
                        "wire-op hash (new entries get 'TODO: justify')")
    p.add_argument("--check", action="append", dest="checks",
                   metavar="ID", choices=list(ALL_CHECKS),
                   help="run only this check id (repeatable)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-checks", action="store_true",
                   help="print the stable check ids and exit")
    args = p.parse_args(argv)

    if args.list_checks:
        for c in ALL_CHECKS:
            print(c)
        return 0

    report = run_lint(root=args.root,
                      baseline_path=args.baseline,
                      checks=args.checks,
                      update_baseline=args.update_baseline,
                      use_baseline=not args.no_baseline)

    if args.as_json:
        try:  # noqa: SIM105 — `| head` closing the pipe is not an error
            _print_json(report)
        except BrokenPipeError:
            pass
        return 0 if report.ok else 1

    for path, err in report.parse_errors:
        print(f"{path}: PARSE ERROR: {err}", file=sys.stderr)
    for f in report.unbaselined:
        print(f.render())
    if args.update_baseline:
        print(f"baseline updated: {len(report.findings)} finding(s) "
              f"recorded, ops hash {report.ops_hash} "
              f"(PROTOCOL_VERSION {report.protocol_version})")
        return 0
    for key in report.stale_baseline_keys:
        print(f"stale baseline entry (finding no longer fires): {key}",
              file=sys.stderr)
    n_sup = len(report.baselined)
    summary = (f"graftlint: {len(report.unbaselined)} finding(s), "
               f"{n_sup} baselined, "
               f"{len(report.stale_baseline_keys)} stale baseline "
               f"entr(ies), ops hash {report.ops_hash}, "
               f"{report.duration_s:.2f}s")
    print(summary)
    return 0 if report.ok else 1


def _print_json(report: LintReport) -> None:
    print(json.dumps({
        "ok": report.ok,
        "ops_hash": report.ops_hash,
        "protocol_version": report.protocol_version,
        "duration_s": round(report.duration_s, 3),
        "unbaselined": [f.__dict__ for f in report.unbaselined],
        "baselined": [f.key for f in report.baselined],
        "stale_baseline_keys": report.stale_baseline_keys,
        "parse_errors": report.parse_errors,
    }, indent=2))


if __name__ == "__main__":
    raise SystemExit(main())
