"""graftlint CLI: ``python -m ray_tpu.tools.lint`` (or ``python -m
ray_tpu lint``).

Exit codes: 0 clean (all findings baselined/suppressed), 1 unbaselined
findings, 2 usage or parse failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

import subprocess

from .analysis import collect_tree
from .baseline import (
    Baseline,
    BaselineJustificationError,
    default_baseline_path,
)
from .cache import LintCache
from .checks import ALL_CHECKS, Finding, protocol_ops_hash, run_checks

# version of the --json output schema; bump on any incompatible change
# to the keys/shapes below (validated by tests/test_static_analysis.py)
JSON_SCHEMA_VERSION = 1


def default_root() -> str:
    """The installed ray_tpu package directory."""
    here = os.path.dirname(os.path.abspath(__file__))   # .../ray_tpu/tools/lint
    return os.path.dirname(os.path.dirname(here))


def default_doc_roots(root: str) -> List[str]:
    repo = os.path.dirname(root)
    out = []
    for cand in (os.path.join(repo, "docs"),
                 os.path.join(repo, "README.md")):
        if os.path.exists(cand):
            out.append(cand)
    return out


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    unbaselined: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline_keys: List[str] = field(default_factory=list)
    pruned_baseline_keys: List[str] = field(default_factory=list)
    parse_errors: List = field(default_factory=list)
    ops_hash: str = ""
    protocol_version: Optional[int] = None
    duration_s: float = 0.0
    changed_only: bool = False
    changed_paths: Optional[List[str]] = None  # None = full tree
    cache_hits: int = 0
    cache_misses: int = 0
    cache_dir: Optional[str] = None  # None = cache disabled

    @property
    def ok(self) -> bool:
        return not self.unbaselined and not self.parse_errors


def _git(repo_dir: str, *args: str) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "-C", repo_dir, *args], capture_output=True,
            text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return out.stdout


def changed_files(root: str) -> Optional[List[str]]:
    """Scan-root-relative paths touched since ``git merge-base`` with
    the upstream (or default) branch, plus any uncommitted/untracked
    work.  None when git state can't be determined (callers fall back
    to the full tree — never silently lint nothing)."""
    repo_dir = os.path.dirname(os.path.abspath(root))
    head = _git(repo_dir, "rev-parse", "HEAD")
    if head is None:
        return None
    repo_top = _git(repo_dir, "rev-parse", "--show-toplevel")
    if repo_top is None:
        return None
    repo_top = repo_top.strip()
    names: set = set()
    # uncommitted (staged + unstaged) and untracked
    for args in (("diff", "--name-only", "HEAD"),
                 ("ls-files", "--others", "--exclude-standard")):
        out = _git(repo_dir, *args)
        if out is None:
            return None
        names.update(ln for ln in out.splitlines() if ln)
    # committed work since the merge-base with the upstream/default branch
    resolved = False
    for base_ref in ("@{upstream}", "origin/main", "origin/master",
                     "main", "master"):
        mb = _git(repo_dir, "merge-base", "HEAD", base_ref)
        if mb is not None:
            mb = mb.strip()
            if mb != head.strip():
                out = _git(repo_dir, "diff", "--name-only", mb, "HEAD")
                if out is None:
                    return None  # can't see branch commits: full tree
                names.update(ln for ln in out.splitlines() if ln)
            resolved = True
            break
    if not resolved:
        # no upstream and no main/master ref: branch-committed files are
        # invisible, and silently dropping them would let the dev-loop
        # gate pass where the full run fails — fall back to the full tree
        return None
    root = os.path.abspath(root)
    rel: List[str] = []
    for name in sorted(names):
        p = os.path.relpath(os.path.join(repo_top, name), root)
        if not p.startswith(".."):
            rel.append(p)
    return rel


def default_cache_dir(root: str) -> str:
    """``.graftlint_cache`` next to the scanned package (inside the
    repo, so lint never touches files outside it)."""
    return os.path.join(os.path.dirname(os.path.abspath(root)),
                        ".graftlint_cache")


def run_lint(root: Optional[str] = None,
             baseline_path: Optional[str] = None,
             doc_roots: Optional[List[str]] = None,
             checks: Optional[List[str]] = None,
             update_baseline: bool = False,
             use_baseline: bool = True,
             justification: Optional[str] = None,
             changed_only: bool = False,
             use_cache: bool = True,
             cache_dir: Optional[str] = None) -> LintReport:
    """Programmatic entry point (the tier-1 test calls this)."""
    t0 = time.monotonic()
    if changed_only and update_baseline:
        raise ValueError(
            "--changed-only cannot be combined with --update-baseline: "
            "a partial view would prune entries for files it never "
            "looked at")
    explicit_root = root is not None
    root = root or default_root()
    if use_baseline and baseline_path is None:
        baseline_path = default_baseline_path()
    if doc_roots is None:
        doc_roots = default_doc_roots(root)
    cache = None
    if use_cache:
        # only cache by default for the installed-package scan; an
        # explicit --root (fixture trees, scratch dirs) must opt in via
        # cache_dir so lint never litters arbitrary directories
        if cache_dir is not None:
            cache = LintCache(cache_dir)
        elif not explicit_root:
            cache = LintCache(default_cache_dir(root))
    changed: Optional[List[str]] = None
    if changed_only:
        changed = changed_files(root)
        # None (git unavailable) falls back to the full tree: the fast
        # mode must only ever UNDER-restrict, never lint nothing
    idx = collect_tree(root, doc_roots=doc_roots, cache=cache)
    baseline = Baseline.load(baseline_path if use_baseline else None)
    findings = run_checks(idx, baseline_protocol=baseline.protocol,
                          checks=checks, cache=cache)
    digest, version = protocol_ops_hash(idx)
    parse_errors = idx.parse_errors
    if changed is not None:
        # the analysis always sees the WHOLE tree (cross-module checks
        # need it); only the reporting narrows to touched files, so the
        # fast mode agrees with the full run on every touched file
        in_changed = set(changed)
        findings = [f for f in findings if f.path in in_changed]
        parse_errors = [(p, e) for p, e in parse_errors
                        if p in in_changed]
    pruned: List[str] = []
    if update_baseline:
        _added, pruned = baseline.absorb(
            findings, {"version": version, "ops_hash": digest},
            ran_checks=checks, justification=justification)
        baseline.path = baseline.path or default_baseline_path()
        baseline.save()
        unbaselined, baselined, stale = [], findings, []
    else:
        unbaselined, baselined, stale = baseline.split(findings)
        if checks:
            # a filtered run cannot judge entries for checks it didn't run
            wanted = set(checks)
            stale = [k for k in stale if k.split(":", 1)[0] in wanted]
        if changed is not None:
            # a changed-only run cannot judge entries for files it
            # didn't report on
            stale = []
    return LintReport(findings=findings, unbaselined=unbaselined,
                      baselined=baselined, stale_baseline_keys=stale,
                      pruned_baseline_keys=pruned,
                      parse_errors=parse_errors,
                      ops_hash=digest, protocol_version=version,
                      duration_s=time.monotonic() - t0,
                      changed_only=changed_only,
                      changed_paths=changed,
                      cache_hits=cache.hits if cache else 0,
                      cache_misses=cache.misses if cache else 0,
                      cache_dir=cache.dir if cache else None)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.lint",
        description=("graftlint: concurrency- and protocol-invariant "
                     "static analyzer for the ray_tpu runtime"))
    p.add_argument("--root", default=None,
                   help="tree to scan (default: the ray_tpu package)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: the checked-in "
                        "ray_tpu/tools/lint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current findings and "
                        "wire-op hash; stale entries are pruned, and NEW "
                        "entries are refused unless --justify is given")
    p.add_argument("--justify", default=None, metavar="REASON",
                   help="justification recorded for every NEW baseline "
                        "entry this --update-baseline run adds")
    p.add_argument("--changed-only", action="store_true",
                   help="report findings only for files changed since "
                        "the git merge-base (plus uncommitted work) — "
                        "the <2s dev-loop gate; analysis still sees the "
                        "whole tree so results match the full run")
    p.add_argument("--check", action="append", dest="checks",
                   metavar="ID", choices=list(ALL_CHECKS),
                   help="run only this check id (repeatable)")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the on-disk result cache (keyed by file "
                        "content hash, invalidated by the lint tool's "
                        "own source digest)")
    p.add_argument("--cache-dir", default=None,
                   help="cache directory (default: .graftlint_cache "
                        "next to the scanned package; explicit --root "
                        "scans only cache when this is given)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-checks", action="store_true",
                   help="print the stable check ids and exit")
    args = p.parse_args(argv)

    if args.list_checks:
        for c in ALL_CHECKS:
            print(c)
        return 0

    try:
        report = run_lint(root=args.root,
                          baseline_path=args.baseline,
                          checks=args.checks,
                          update_baseline=args.update_baseline,
                          use_baseline=not args.no_baseline,
                          justification=args.justify,
                          changed_only=args.changed_only,
                          use_cache=not args.no_cache,
                          cache_dir=args.cache_dir)
    except BaselineJustificationError as e:
        print(f"refusing to update baseline: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.as_json:
        try:  # noqa: SIM105 — `| head` closing the pipe is not an error
            _print_json(report)
        except BrokenPipeError:
            pass
        return 0 if report.ok else 1

    for path, err in report.parse_errors:
        print(f"{path}: PARSE ERROR: {err}", file=sys.stderr)
    for f in report.unbaselined:
        print(f.render())
    if args.update_baseline:
        for key in report.pruned_baseline_keys:
            print(f"pruned stale baseline entry: {key}", file=sys.stderr)
        print(f"baseline updated: {len(report.findings)} finding(s) "
              f"recorded, {len(report.pruned_baseline_keys)} stale "
              f"entr(ies) pruned, ops hash {report.ops_hash} "
              f"(PROTOCOL_VERSION {report.protocol_version})")
        return 0
    for key in report.stale_baseline_keys:
        print(f"stale baseline entry (finding no longer fires): {key}",
              file=sys.stderr)
    n_sup = len(report.baselined)
    scope = ""
    if report.changed_only:
        scope = (f" [changed-only: {len(report.changed_paths or [])} "
                 "file(s)]" if report.changed_paths is not None
                 else " [changed-only: git unavailable, full tree]")
    cache_note = ""
    if report.cache_dir is not None:
        cache_note = (f", cache {report.cache_hits} hit(s)/"
                      f"{report.cache_misses} miss(es)")
    summary = (f"graftlint: {len(report.unbaselined)} finding(s), "
               f"{n_sup} baselined, "
               f"{len(report.stale_baseline_keys)} stale baseline "
               f"entr(ies), ops hash {report.ops_hash}, "
               f"{report.duration_s:.2f}s{cache_note}{scope}")
    print(summary)
    return 0 if report.ok else 1


def report_as_dict(report: LintReport) -> dict:
    """The versioned --json payload (schema_version
    :data:`JSON_SCHEMA_VERSION`; shape validated by
    tests/test_static_analysis.py — bump the version on any
    incompatible change)."""
    return {
        "schema_version": JSON_SCHEMA_VERSION,
        "ok": report.ok,
        "ops_hash": report.ops_hash,
        "protocol_version": report.protocol_version,
        "duration_s": round(report.duration_s, 3),
        "unbaselined": [f.__dict__ for f in report.unbaselined],
        "baselined": [f.key for f in report.baselined],
        "stale_baseline_keys": report.stale_baseline_keys,
        "pruned_baseline_keys": report.pruned_baseline_keys,
        "parse_errors": report.parse_errors,
        "changed_only": report.changed_only,
        "changed_paths": report.changed_paths,
        "cache": {
            "enabled": report.cache_dir is not None,
            "dir": report.cache_dir,
            "hits": report.cache_hits,
            "misses": report.cache_misses,
        },
    }


def _print_json(report: LintReport) -> None:
    print(json.dumps(report_as_dict(report), indent=2))


if __name__ == "__main__":
    raise SystemExit(main())
