"""Head-side client server: remote drivers over TCP (Ray Client analog).

Reference: ``ray://`` client mode — a gRPC proxy/server pair
(python/ray/util/client/server/server.py, proxier.py) through which a
remote ``ray.init(address="ray://...")`` driver submits tasks, puts/gets
objects, and manages actors on a running cluster. Here the transport is
the same authenticated TCP channel protocol the node daemons use
(core/protocol.py); each connected client gets a session with its own
job id and a pin ledger, so a dying client releases its object pins.

Job submission (job_manager.py) rides on this: a submitted job's driver
subprocess connects back as a client.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .ids import ActorID, JobID, ObjectID, TaskID
from .protocol import Channel, make_listener


class _ClientSession:
    """One connected remote driver."""

    def __init__(self, server: "ClientServer", channel: Channel):
        self.server = server
        self.head = server.head
        self.channel = channel
        self.job_id = JobID.from_random()
        self.driver_task_id = TaskID.for_driver_task(self.job_id)
        self.put_counter = 0
        self.pins: Dict[ObjectID, int] = {}
        self.lock = threading.Lock()
        self.closed = False

    # ---- ref ledger -------------------------------------------------------
    def pin(self, oid: ObjectID) -> None:
        with self.lock:
            self.pins[oid] = self.pins.get(oid, 0) + 1
        with self.head._lock:
            self.head.ref_counts[oid] += 1

    def unpin(self, oid: ObjectID) -> None:
        with self.lock:
            cur = self.pins.get(oid, 0)
            if cur <= 1:
                self.pins.pop(oid, None)
            else:
                self.pins[oid] = cur - 1
        with self.head._lock:
            self.head.ref_counts[oid] -= 1
            dead = self.head.ref_counts[oid] <= 0
        if dead and not self.head._stopped:
            self.head.delete_object(oid)

    def release_all(self) -> None:
        with self.lock:
            pins, self.pins = self.pins, {}
        for oid, n in pins.items():
            with self.head._lock:
                self.head.ref_counts[oid] -= n
                dead = self.head.ref_counts[oid] <= 0
            if dead and not self.head._stopped:
                try:
                    self.head.delete_object(oid)
                except Exception:
                    pass

    # ---- ops --------------------------------------------------------------
    def op_put(self, data: bytes):
        from .config import global_config

        with self.lock:
            self.put_counter += 1
            idx = self.put_counter
        oid = ObjectID.for_put(self.driver_task_id, idx)
        node = self.head.head_node
        if len(data) <= global_config().max_direct_call_object_size:
            node.store.put_inline(oid, bytes(data), False)
        else:
            _, view = node.store.create(oid, len(data))
            view[: len(data)] = data
            node.store.seal(oid, False)
        self.head.on_object_sealed(oid, node.hex)
        return oid

    def op_get(self, oid: ObjectID, timeout: Optional[float]):
        payload, is_error = self.head.get_object_payload(oid, timeout)
        return bytes(payload), is_error

    def dispatch(self, op: str, args: tuple) -> Any:
        head = self.head
        if op == "put":
            return self.op_put(args[0])
        if op == "get":
            return self.op_get(args[0], args[1])
        if op == "wait":
            return head.wait_objects(args[0], args[1], args[2])
        if op == "submit":
            spec = args[0]
            spec.job_id = self.job_id
            head.submit_spec(spec)
            return None
        if op == "register_function":
            head.gcs.register_function(args[0], args[1])
            return None
        if op == "get_function":
            return head.gcs.get_function(args[0])
        if op == "create_actor":
            return head.create_actor(*args)
        if op == "get_actor_info":
            info = head.gcs.get_named_actor(args[0], args[1])
            if info is None or info.state == "DEAD":
                return None
            return {"actor_id": info.actor_id,
                    "class_name": info.class_name,
                    "max_task_retries": info.max_task_retries}
        if op == "kill_actor":
            return head.kill_actor(args[0], args[1])
        if op == "cancel":
            return head.cancel_task(args[0], args[1])
        if op == "kv":
            return getattr(head.gcs, "kv_" + args[0])(*args[1])
        if op == "stream_next":
            owner = args[3] if len(args) > 3 else None
            if owner is not None:
                # owner-published stream: subscribe via the head node's
                # routing (worker/peer channels), not head records
                return head.head_node.serve_stream_sub(
                    owner, args[0], args[1], args[2] or 2.0)
            return head.stream_next(args[0], args[1], args[2])
        if op == "avail":
            return head.scheduler.available_resources()
        if op == "total":
            return head.scheduler.total_resources()
        if op == "nodes":
            return [{"NodeID": n.hex, "Alive": n.alive,
                     "Resources": n.resources_total, "Labels": n.labels}
                    for n in head.gcs.nodes.values()]
        if op == "create_pg":
            pg = head.scheduler.create_placement_group(*args)
            return pg.pg_id
        if op == "pg_op":
            return head.handle_worker_rpc(None, None, "pg_" + args[0],
                                          args[1])
        if op == "state_list":
            return head.state_list(args[0], args[1])
        if op in ("pub_publish", "pub_poll", "pub_cursor"):
            return head.handle_worker_rpc(None, None, op, args)
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown client op {op!r}")

    # ---- serve loop -------------------------------------------------------
    def _dispatch_and_reply(self, req_id: int, op: str, args: tuple) -> None:
        try:
            value, ok = self.dispatch(op, args), True
        except BaseException as e:  # noqa: BLE001
            value, ok = e, False
        try:
            self.channel.send("reply", req_id, ok, value)
        except (OSError, ConnectionError):
            pass  # client went away
        except Exception:
            # result unpicklable: send the repr as an error
            try:
                self.channel.send(
                    "reply", req_id, False,
                    RuntimeError(f"unserializable reply for {op}: "
                                 f"{type(value).__name__}"))
            except Exception:
                pass

    # ops that can block indefinitely (a full pool of them must never be
    # able to queue the submit that would unblock them)
    _BLOCKING_OPS = frozenset({"get", "wait", "stream_next"})

    def serve(self) -> None:
        """Reader loop. Quick ops share a small per-session pool;
        potentially long-blocking ops (get/wait/stream_next) go to a much
        larger dedicated pool — its capacity bounds how many of a client's
        threads may block in get() simultaneously without starving the
        submit that would unblock them, while reusing threads (stream_next
        arrives once per streamed item)."""
        from concurrent.futures import ThreadPoolExecutor

        prefix = f"client-{self.job_id.hex()[:6]}"
        pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix=prefix)
        blocking_pool = ThreadPoolExecutor(
            max_workers=256, thread_name_prefix=prefix + "-blk")
        try:
            while not self.server._stopped:
                tag, payload = self.channel.recv()
                if tag == "rpc":
                    req_id, op, *args = payload
                    target = (blocking_pool if op in self._BLOCKING_OPS
                              else pool)
                    target.submit(self._dispatch_and_reply, req_id, op,
                                  tuple(args))
                elif tag == "refop":
                    kind, oid = payload
                    (self.pin if kind == "add" else self.unpin)(oid)
                elif tag == "bye":
                    break
        except (EOFError, OSError, ConnectionError):
            pass
        finally:
            self.closed = True
            pool.shutdown(wait=False)
            blocking_pool.shutdown(wait=False)
            self.release_all()
            try:
                self.channel.close()
            except Exception:
                pass
            self.server._forget(self)


class ClientServer:
    """Accept loop for remote-driver sessions."""

    def __init__(self, head, host: str = "0.0.0.0", port: int = 0):
        self.head = head
        self._stopped = False
        if head._cluster_key is None:
            # client server implies a TCP cluster: bring the node server up
            # (on the same interface, so remote nodes can reach it too)
            head.start_node_server(host="0.0.0.0" if host != "127.0.0.1"
                                   else "127.0.0.1")
        self._listener = make_listener((host, port), head._cluster_key)
        self.address = self._listener.address
        self.sessions = []
        self._sessions_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="client-server", daemon=True)
        self._thread.start()

    def _forget(self, sess: "_ClientSession") -> None:
        with self._sessions_lock:
            try:
                self.sessions.remove(sess)
            except ValueError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError, Exception):
                if self._stopped:
                    return
                time.sleep(0.05)
                continue
            ch = Channel(conn)
            sess = _ClientSession(self, ch)
            from .protocol import PROTOCOL_VERSION

            try:
                ch.send("welcome", {
                    "job_id": sess.job_id,
                    "node_id": self.head.head_node.hex,
                    "driver_task_id": sess.driver_task_id,
                    "proto": PROTOCOL_VERSION,
                })
            except Exception:
                continue
            with self._sessions_lock:
                self.sessions.append(sess)
            threading.Thread(target=sess.serve, daemon=True,
                             name=f"client-{sess.job_id.hex()[:6]}").start()

    def stop(self) -> None:
        self._stopped = True
        from .protocol import close_listener

        close_listener(self._listener)  # wakes the parked accept()
        with self._sessions_lock:
            sessions = list(self.sessions)
        for sess in sessions:
            try:
                sess.channel.close()  # unblocks the reader -> clean teardown
            except Exception:
                pass
        # the closed listener pops the accept loop; reap it
        self._thread.join(timeout=2.0)
