"""Dynamic lock-order assertions (``RAY_TPU_DEBUG_LOCK_ORDER=1``).

The runtime counterpart of graftlint's static ``lock-order`` check: the
static pass derives the lock-acquisition graph from ``with self._lock``
nesting and flags cycles; this module *validates that order while the
code actually runs*.  Every lock created through :func:`tracked_lock` /
:func:`tracked_rlock` maintains

- a **thread-local acquisition stack** (which tracked locks this thread
  currently holds, in order), and
- a **process-global order graph**: an edge ``A -> B`` is recorded the
  first time any thread acquires ``B`` while holding ``A``.

Acquiring ``B`` while holding ``A`` when a path ``B ->* A`` already
exists in the graph is an inversion — two lock sites disagree about the
global order, which is a deadlock waiting for the right interleaving —
and raises :class:`LockOrderViolation` *immediately, on the acquiring
thread*, instead of wedging a production cluster days later.  Unlike an
actual deadlock, a single thread exercising both orders is enough to
trip the assertion, which is what makes it usable from unit tests.

Off by default: with ``debug_lock_order`` false the factories return
plain ``threading`` primitives with zero overhead.  The flag rides the
Config snapshot, so enabling it on the head enables it cluster-wide.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set

__all__ = [
    "LockOrderViolation",
    "tracked_lock",
    "tracked_rlock",
    "reset_order_graph",
    "held_locks",
]


class LockOrderViolation(RuntimeError):
    """Two tracked locks were acquired in both orders (potential deadlock)."""


# first-observed acquisition order: edges outer -> inner
_edges: Dict[str, Set[str]] = {}
_edges_lock = threading.Lock()
# where each edge was first recorded, for the violation message
_edge_origin: Dict[tuple, str] = {}
_tls = threading.local()


def _stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def held_locks() -> List[str]:
    """Names of tracked locks the calling thread currently holds."""
    return list(_stack())


def reset_order_graph() -> None:
    """Forget every recorded edge (test isolation)."""
    with _edges_lock:
        _edges.clear()
        _edge_origin.clear()


def _reaches(src: str, dst: str) -> List[str]:
    """Path src ->* dst in the order graph, [] if none.  Caller holds
    ``_edges_lock``."""
    parent = {src: None}
    queue = [src]
    while queue:
        cur = queue.pop(0)
        for nxt in _edges.get(cur, ()):
            if nxt in parent:
                continue
            parent[nxt] = cur
            if nxt == dst:
                path = [nxt]
                while parent[path[-1]] is not None:
                    path.append(parent[path[-1]])
                return list(reversed(path))
            queue.append(nxt)
    return []


def _note_acquire(name: str) -> None:
    st = _stack()
    for outer in st:
        if outer == name:
            continue  # reentrant acquire: no ordering information
        with _edges_lock:
            if name in _edges.get(outer, ()):  # noqa: SIM108
                continue  # edge already known
            inv = _reaches(name, outer)
            if inv:
                origin = _edge_origin.get((inv[0], inv[1]), "?")
                raise LockOrderViolation(
                    f"lock order inversion: acquiring {name!r} while "
                    f"holding {outer!r}, but the opposite order "
                    f"{' -> '.join(inv)} was already observed "
                    f"(first at {origin}); pick one global order for "
                    "these locks")
            _edges.setdefault(outer, set()).add(name)
            import traceback

            frame = traceback.extract_stack(limit=4)[0]
            _edge_origin[(outer, name)] = \
                f"{frame.filename}:{frame.lineno}"
    st.append(name)


def _note_release(name: str) -> None:
    st = _stack()
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


class _TrackedLock:
    """Order-asserting wrapper around a threading lock.  Exposes the
    subset of the lock protocol the runtime uses (``with``, explicit
    acquire/release, and enough surface for ``threading.Condition`` to
    fall back to its acquire/release-based wait implementation)."""

    __slots__ = ("_name", "_inner")

    def __init__(self, name: str, inner):
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquire(self._name)
            except LockOrderViolation:
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False

    # --- threading.Condition integration -------------------------------
    # Condition(lock) probes these; without them its acquire(False)-based
    # fallbacks misbehave on a wrapped RLock (a reentrant acquire(False)
    # succeeds, so the fallback _is_owned would report "not owned" for a
    # lock this thread holds).

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        # Condition.wait parks: ALL recursion levels drop at once, so
        # scrub every instance of this lock from the acquisition stack
        # and remember how many to restore.
        inner = self._inner
        if hasattr(inner, "_release_save"):
            inner_state = inner._release_save()
        else:
            inner.release()
            inner_state = None
        st = _stack()
        count = st.count(self._name)
        while self._name in st:
            st.remove(self._name)
        return (inner_state, count)

    def _acquire_restore(self, state) -> None:
        inner_state, count = state
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(inner_state)
        else:
            inner.acquire()
        _stack().extend([self._name] * max(count, 1))

    def __repr__(self):
        return f"<TrackedLock {self._name} wrapping {self._inner!r}>"


def _enabled() -> bool:
    from .config import global_config

    return bool(global_config().debug_lock_order)


def tracked_lock(name: str):
    """``threading.Lock()`` — order-tracked under RAY_TPU_DEBUG_LOCK_ORDER."""
    if not _enabled():
        return threading.Lock()
    return _TrackedLock(name, threading.Lock())


def tracked_rlock(name: str):
    """``threading.RLock()`` — order-tracked under RAY_TPU_DEBUG_LOCK_ORDER."""
    if not _enabled():
        return threading.RLock()
    return _TrackedLock(name, threading.RLock())
