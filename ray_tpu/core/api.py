"""Public driver API: init/shutdown/remote/get/put/wait/kill/cancel/...

Analog of ``python/ray/_private/worker.py`` (ray.init :1227, get/put/wait
wrappers) in the reference, minus process spawning for the control plane —
the head runs in the driver process and worker processes are forked per node
(see node.py).
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from . import object_ref as object_ref_mod
from . import runtime as runtime_mod
from .actor import ActorClass, ActorHandle, method  # noqa: F401
from .exceptions import GetTimeoutError
from .ids import ActorID
from .object_ref import ObjectRef
from .remote_function import RemoteFunction
from .runtime import DriverRuntime, Head


_head: Optional[Head] = None
_namespace: str = "default"


def is_initialized() -> bool:
    return runtime_mod.get_current_runtime() is not None


def init(
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    num_gpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    ignore_reinit_error: bool = False,
    address: Optional[str] = None,
    cluster_key: Optional[str] = None,
    storage: Optional[str] = None,
    local_mode: bool = False,
    **_kwargs,
):
    """Start a single-node cluster in-process and connect the driver —
    or, with ``address="ray_tpu://host:port"``, connect this process as a
    *remote* driver to a running head (Ray Client analog; reference:
    ``ray.init(address="ray://...")``). ``cluster_key`` (hex; or env
    ``RAY_TPU_CLUSTER_KEY``) authenticates the channel."""
    global _head, _namespace
    if is_initialized():
        if ignore_reinit_error:
            return runtime_mod.get_current_runtime()
        raise RuntimeError("ray_tpu.init() called twice")
    if local_mode:
        # inline debugging mode (reference: ray.init(local_mode=True)) —
        # tasks/actors execute synchronously in this process
        from .local_mode import LocalModeRuntime

        _namespace = namespace
        rt = LocalModeRuntime(namespace)
        runtime_mod.set_current_runtime(rt)
        object_ref_mod.set_runtime(rt)
        return rt
    address = address or os.environ.get("RAY_TPU_ADDRESS")
    if address and address not in ("local", "auto"):
        from .client_runtime import ClientRuntime

        if address.startswith("ray_tpu://"):
            address = address[len("ray_tpu://"):]
        key_hex = cluster_key or os.environ.get("RAY_TPU_CLUSTER_KEY", "")
        if not key_hex:
            raise ValueError(
                "connecting to a remote head requires cluster_key= or "
                "RAY_TPU_CLUSTER_KEY")
        _namespace = namespace
        rt = ClientRuntime(address, bytes.fromhex(key_hex))
        runtime_mod.set_current_runtime(rt)
        object_ref_mod.set_runtime(rt)
        return rt
    from .config import global_config
    from .accelerators import detect_resources

    if object_store_memory:
        global_config().object_store_memory = int(object_store_memory)
    total = detect_resources(num_cpus=num_cpus, num_tpus=num_tpus,
                             num_gpus=num_gpus, extra=resources)
    _namespace = namespace
    from ray_tpu.util.usage_stats import mark_session_started

    mark_session_started()  # no-op unless RAY_TPU_USAGE_STATS_ENABLED=1
    _head = Head(total, labels=labels, storage=storage)
    rt = DriverRuntime(_head)
    runtime_mod.set_current_runtime(rt)
    object_ref_mod.set_runtime(rt)
    if global_config().device_telemetry_enabled:
        # driver-process JAX device gauges land in the head registry
        from ray_tpu.util.device_telemetry import (observe_jax_import,
                                                    start_device_telemetry)

        observe_jax_import()  # compile events from process start, not tick 1
        _head._device_telemetry_stop = start_device_telemetry(
            node_hex=_head.head_node.hex)
    return rt


def shutdown():
    global _head
    rt = runtime_mod.get_current_runtime()
    if rt is None:
        return
    runtime_mod.set_current_runtime(None)
    object_ref_mod.set_runtime(None)
    if getattr(rt, "mode", None) in ("CLIENT", "LOCAL"):
        rt.disconnect()
        return
    if _head is not None:
        cs = getattr(_head, "_client_server", None)
        if cs is not None:
            cs.stop()
            _head._client_server = None
        _head.shutdown()
        _head = None
        try:
            from ray_tpu.util.usage_stats import flush

            flush()  # local-only, opt-in (RAY_TPU_USAGE_STATS_ENABLED)
        except Exception:
            pass  # telemetry must never break shutdown


def start_client_server(host: str = "127.0.0.1", port: int = 0):
    """Start the head-side remote-driver server (Ray Client analog).

    Returns ((host, port), cluster_key_hex) — hand these to remote
    drivers: ``ray_tpu.init(address=f"ray_tpu://{host}:{port}",
    cluster_key=key)``.
    """
    head = _get_head()
    from .client_server import ClientServer

    if getattr(head, "_client_server", None) is None:
        head._client_server = ClientServer(head, host, port)
    return head._client_server.address, head.cluster_key_hex


def _get_head() -> Head:
    if _head is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _head


def remote(*args, **options):
    """``@remote`` decorator for functions and classes (reference:
    python/ray/_private/worker.py remote)."""

    def make(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and not options and (inspect.isfunction(args[0])
                                           or inspect.isclass(args[0])):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return make


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    rt = runtime_mod.get_current_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    single = isinstance(refs, ObjectRef)
    lst = [refs] if single else list(refs)
    for r in lst:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
    values = rt.get(lst, timeout=timeout)
    return values[0] if single else values


def put(value: Any) -> ObjectRef:
    rt = runtime_mod.get_current_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    if isinstance(value, ObjectRef):
        raise TypeError("put() on an ObjectRef is not allowed")
    return rt.put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    rt = runtime_mod.get_current_runtime()
    lst = list(refs)
    if num_returns > len(lst):
        raise ValueError("num_returns exceeds number of refs")
    return rt.wait(lst, num_returns=num_returns, timeout=timeout,
                   fetch_local=fetch_local)


def get_object_locations(refs: Sequence[ObjectRef]) -> Dict[ObjectRef, List[str]]:
    """Node hexes currently holding each object (may be empty for inline
    or in-flight objects). The data plane uses this for locality-aware
    dispatch and split dealing; works from the driver and from workers
    (reference: ray.experimental.get_object_locations)."""
    rt = runtime_mod.get_current_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    lst = list(refs)
    for r in lst:
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"get_object_locations() expects ObjectRefs, got {type(r)}")
    lookup = getattr(rt, "object_locations", None)
    if lookup is None:  # e.g. local_mode: everything is in-process
        return {r: [] for r in lst}
    locs = lookup([r.id for r in lst])
    return {r: list(ls) for r, ls in zip(lst, locs)}


def kill(actor: ActorHandle, *, no_restart: bool = True):
    rt = runtime_mod.get_current_runtime()
    rt.kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    rt = runtime_mod.get_current_runtime()
    rt.cancel_task(ref.id, force)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    rt = runtime_mod.get_current_runtime()
    info = rt.get_actor_info(name, namespace or _namespace)
    if info is None:
        raise ValueError(f"Failed to look up actor {name!r}")
    return ActorHandle(info["actor_id"], info["class_name"],
                       max_task_retries=info.get("max_task_retries", 0) or 0)


def available_resources() -> Dict[str, float]:
    return runtime_mod.get_current_runtime().available_resources()


def cluster_resources() -> Dict[str, float]:
    return runtime_mod.get_current_runtime().cluster_resources()


def nodes() -> List[dict]:
    return runtime_mod.get_current_runtime().nodes()
