"""Global Control Service — the cluster control plane.

Analog of the reference's GCS server (``src/ray/gcs/gcs_server/``): node table
with health, actor table + lifecycle FSM, job table, function table, internal
KV, object directory, named-actor registry, pubsub, and a task-event sink for
observability (reference: gcs_task_manager.h:86). Here it is an in-process
thread-safe service owned by the head; workers reach it through their node's
RPC channel, exactly as raylets/workers reach the GCS over gRPC in the
reference. Pluggable persistence (in-memory now; the interface mirrors
``store_client`` so a redis/file backend can drop in for GCS fault tolerance).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from .config import global_config
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    class_name: str
    state: str  # PENDING_CREATION | ALIVE | RESTARTING | DEAD
    node_hex: Optional[str] = None
    worker_id: Optional[bytes] = None
    max_restarts: int = 0
    num_restarts: int = 0
    max_task_retries: int = 0
    death_cause: Optional[str] = None
    detached: bool = False
    creation_spec: Any = None  # retained for restart (lineage)


@dataclass
class NodeInfo:
    node_id: NodeID
    hex: str
    alive: bool = True
    resources_total: Dict[str, float] = field(default_factory=dict)
    last_heartbeat: float = field(default_factory=time.monotonic)
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class JobInfo:
    job_id: JobID
    entrypoint: str = "driver"
    state: str = "RUNNING"
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None


@dataclass
class TaskEvent:
    task_id: bytes
    name: str
    state: str
    node_hex: Optional[str]
    ts: float
    attempt: int = 0
    error: Optional[str] = None


class PubSub:
    """In-process publisher with per-channel subscriptions (reference:
    src/ray/pubsub/ long-poll publisher; here callbacks fire inline)."""

    def __init__(self):
        self._subs: Dict[str, List[Callable]] = defaultdict(list)
        self._lock = threading.Lock()

    def subscribe(self, channel: str, callback: Callable) -> None:
        with self._lock:
            self._subs[channel].append(callback)

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass


class GCS:
    def __init__(self, store=None):
        from .gcs_store import InMemoryStore

        self._lock = threading.RLock()
        self._store = store or InMemoryStore()
        # durable-table writes only happen against a real backend: the
        # default InMemoryStore would no-op them anyway, but the object
        # directory rides the seal hot path, so even building the
        # journal record must be skipped when nothing persists it
        self._durable = not isinstance(self._store, InMemoryStore)
        self.kv: Dict[str, Dict[bytes, bytes]] = defaultdict(dict)  # namespace -> kv
        self.functions: Dict[str, bytes] = {}  # function_id -> pickled fn/class
        # recover durable tables (reference: GCS restart w/ RedisStoreClient)
        recovered = self._store.load()
        for (ns, key), value in recovered.get("kv", {}).items():
            self.kv[ns][key] = value
        self.functions.update(recovered.get("functions", {}))
        self._recovered_jobs = recovered.get("jobs", {})
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name) -> id
        self.nodes: Dict[str, NodeInfo] = {}
        self.jobs: Dict[JobID, JobInfo] = {}
        # prior-session jobs from durable storage, shown DEAD (their
        # drivers did not survive the head restart)
        for job_hex, rec in self._recovered_jobs.items():
            try:
                jid = JobID(bytes.fromhex(job_hex))
                self.jobs[jid] = JobInfo(
                    jid, entrypoint=rec.get("entrypoint", "driver"),
                    state="DEAD", start_time=rec.get("start_time", 0.0))
            except Exception:
                pass
        self.object_dir: Dict[ObjectID, Set[str]] = defaultdict(set)  # oid -> node hexes
        # ---- restart recovery of the PR-7-era control tables ----------
        # actor records (incl. pickled creation specs for restartable /
        # detached actors), the named-actor registry (rebuilt from live
        # records), the object directory (locations go live again only
        # when their node re-registers — every lookup filters on
        # head.nodes membership), and placement specs. The Head decides
        # what to DO with these (re-create detached actors, fail the
        # rest); this layer only rehydrates them.
        self._rehydrate_actors_objdir(recovered)
        self.recovered_placements: Dict[str, dict] = \
            dict(recovered.get("placements", {}))
        self.meta: Dict[str, Any] = dict(recovered.get("meta", {}))
        self.pubsub = PubSub()
        cfg = global_config()
        self.task_events: deque = deque(maxlen=cfg.task_events_max_buffered)
        # structured cluster events (util/events.py; reference: the GCS
        # cluster-event table behind `ray list cluster-events`)
        self.cluster_events: deque = deque(
            maxlen=cfg.cluster_events_max_buffered)
        self.placement_groups: Dict[PlacementGroupID, Any] = {}

    # ---- KV (reference: gcs_kv_manager.cc) ----
    def kv_put(self, key: bytes, value: bytes, namespace: str = "default", overwrite=True) -> bool:
        with self._lock:
            ns = self.kv[namespace]
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self._store.put("kv", (namespace, key), value)
            return True

    def kv_get(self, key: bytes, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self.kv[namespace].get(key)

    def kv_del(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            existed = self.kv[namespace].pop(key, None) is not None
            if existed:
                self._store.delete("kv", (namespace, key))
            return existed

    def kv_keys(self, prefix: bytes, namespace: str = "default") -> List[bytes]:
        with self._lock:
            return [k for k in self.kv[namespace] if k.startswith(prefix)]

    def kv_exists(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            return key in self.kv[namespace]

    # ---- functions (reference: gcs_function_manager.h) ----
    def register_function(self, function_id: str, payload: bytes) -> None:
        with self._lock:
            self.functions[function_id] = payload
            self._store.put("functions", function_id, payload)

    def get_function(self, function_id: str) -> Optional[bytes]:
        with self._lock:
            return self.functions.get(function_id)

    # ---- nodes (reference: gcs_node_manager.cc) ----
    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self.nodes[info.hex] = info
        self.pubsub.publish("node", ("added", info.hex))

    def mark_node_dead(self, node_hex: str) -> None:
        with self._lock:
            info = self.nodes.get(node_hex)
            if info is None or not info.alive:
                return
            info.alive = False
        self.pubsub.publish("node", ("removed", node_hex))

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # ---- actors (reference: gcs_actor_manager.cc FSM) ----
    def _persist_actor_locked(self, info: ActorInfo) -> None:
        """Journal one actor record (reference: the GCS actor table the
        RedisStoreClient makes restart-durable). ``creation_spec`` is
        already pickled bytes — the restart seed for detached actors."""
        if not self._durable:
            return
        self._store.put("actors", info.actor_id.binary(), {
            "name": info.name, "namespace": info.namespace,
            "class_name": info.class_name, "state": info.state,
            "node_hex": info.node_hex,
            "max_restarts": info.max_restarts,
            "num_restarts": info.num_restarts,
            "max_task_retries": info.max_task_retries,
            "death_cause": info.death_cause, "detached": info.detached,
            "creation_spec": info.creation_spec,
        })

    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            self.actors[info.actor_id] = info
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    raise ValueError(f"actor name {info.name!r} already taken")
                self.named_actors[key] = info.actor_id
            self._persist_actor_locked(info)

    def update_actor(self, actor_id: ActorID, **fields_) -> None:
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None:
                return
            for k, v in fields_.items():
                setattr(info, k, v)
            state = fields_.get("state")
            self._persist_actor_locked(info)
        if state:
            self.pubsub.publish("actor", (actor_id, state))

    def get_actor(self, actor_id: ActorID) -> Optional[ActorInfo]:
        with self._lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "default") -> Optional[ActorInfo]:
        with self._lock:
            aid = self.named_actors.get((namespace, name))
            return self.actors.get(aid) if aid else None

    def remove_actor_name(self, actor_id: ActorID) -> None:
        with self._lock:
            info = self.actors.get(actor_id)
            if info and info.name:
                self.named_actors.pop((info.namespace, info.name), None)

    def list_actors(self) -> List[ActorInfo]:
        with self._lock:
            return list(self.actors.values())

    # ---- jobs ----
    def add_job(self, info: JobInfo) -> None:
        with self._lock:
            self.jobs[info.job_id] = info
            self._store.put("jobs", info.job_id.hex(), {
                "entrypoint": info.entrypoint, "state": info.state,
                "start_time": info.start_time})

    def close(self) -> None:
        self._store.close()

    # ---- object directory (reference: ownership_based_object_directory.cc) ----
    def _persist_objdir_locked(self, oid: ObjectID) -> None:
        if not self._durable:
            return
        locs = self.object_dir.get(oid)
        if locs:
            self._store.put("objdir", oid.binary(), sorted(locs))
        else:
            self._store.delete("objdir", oid.binary())

    def add_object_location(self, oid: ObjectID, node_hex: str) -> None:
        with self._lock:
            locs = self.object_dir[oid]
            if node_hex not in locs:
                locs.add(node_hex)
                self._persist_objdir_locked(oid)
        self.pubsub.publish("object", (oid, node_hex))

    def remove_object_location(self, oid: ObjectID, node_hex: str) -> None:
        with self._lock:
            locs = self.object_dir.get(oid)
            if locs and node_hex in locs:
                locs.discard(node_hex)
                if not locs:
                    del self.object_dir[oid]
                self._persist_objdir_locked(oid)

    def get_object_locations(self, oid: ObjectID) -> Set[str]:
        with self._lock:
            return set(self.object_dir.get(oid, ()))

    def drop_node_objects(self, node_hex: str) -> List[ObjectID]:
        """On node death: purge its locations; return objects now location-less."""
        lost = []
        with self._lock:
            for oid in list(self.object_dir):
                locs = self.object_dir[oid]
                if node_hex not in locs:
                    continue
                locs.discard(node_hex)
                if not locs:
                    del self.object_dir[oid]
                    lost.append(oid)
                self._persist_objdir_locked(oid)
        return lost

    def _rehydrate_actors_objdir(self, recovered: dict) -> None:
        """The one place durable actor records and object-directory
        entries become live state — cold-start recovery (__init__) and
        bounce reload both ride it, so a new journal field can never
        silently diverge the two paths."""
        for aid_bin, rec in recovered.get("actors", {}).items():
            try:
                info = ActorInfo(
                    actor_id=ActorID(aid_bin), name=rec.get("name"),
                    namespace=rec.get("namespace", "default"),
                    class_name=rec.get("class_name", ""),
                    state=rec.get("state", "DEAD"),
                    node_hex=rec.get("node_hex"),
                    max_restarts=rec.get("max_restarts", 0),
                    num_restarts=rec.get("num_restarts", 0),
                    max_task_retries=rec.get("max_task_retries", 0),
                    death_cause=rec.get("death_cause"),
                    detached=rec.get("detached", False),
                    creation_spec=rec.get("creation_spec"))
                self.actors[info.actor_id] = info
                if info.name and info.state != "DEAD":
                    self.named_actors[(info.namespace, info.name)] = \
                        info.actor_id
            except Exception:
                pass  # one unreadable record must not poison recovery
        for oid_bin, hexes in recovered.get("objdir", {}).items():
            try:
                self.object_dir[ObjectID(oid_bin)] = set(hexes)
            except Exception:
                pass

    def reload_from_store(self) -> None:
        """Head-bounce support: REPLACE the durable-table views with what
        the journal actually holds — the restarted head must run off
        recovered state, not off conveniently-surviving process memory
        (that is what makes the bounce an honest persistence test). The
        in-memory and journaled views are written synchronously, so on a
        healthy journal this round-trips; a divergence is exactly the
        bug the chaos suite exists to catch. No-op without a durable
        backend (daemon replay alone carries an in-memory bounce)."""
        if not self._durable:
            return
        recovered = self._store.load()
        with self._lock:
            self.kv.clear()
            for (ns, key), value in recovered.get("kv", {}).items():
                self.kv[ns][key] = value
            self.functions = dict(recovered.get("functions", {}))
            self.actors.clear()
            self.named_actors.clear()
            self.object_dir.clear()
            self._rehydrate_actors_objdir(recovered)
            self.meta = dict(recovered.get("meta", {}))

    # ---- restart metadata + placement specs (durable) ----
    def set_meta(self, key: str, value: Any) -> None:
        """Small durable restart metadata: head epoch, deferred-delete
        set, daemon lease views (journaled on their natural cadence)."""
        with self._lock:
            self.meta[key] = value
            if self._durable:
                self._store.put("meta", key, value)

    def persist_placement(self, pg_id_hex: str,
                          rec: Optional[dict]) -> None:
        """Journal (or, with ``rec=None``, retire) one placement-group
        spec — the restart seed for re-reserving bundles."""
        if not self._durable:
            return
        with self._lock:
            if rec is None:
                self._store.delete("placements", pg_id_hex)
            else:
                self._store.put("placements", pg_id_hex, rec)

    # ---- task events (reference: gcs_task_manager.h) ----
    def record_task_event(self, ev: TaskEvent) -> None:
        if global_config().task_events_enabled:
            self.task_events.append(ev)

    def list_task_events(self, limit: int = 1000) -> List[TaskEvent]:
        with self._lock:
            return list(self.task_events)[-limit:]

    # ---- cluster events (util/events.py sink; reference: the GCS
    # cluster-event table behind `ray list cluster-events`) ----
    def record_cluster_event(self, ev: dict) -> None:
        self.cluster_events.append(ev)

    def list_cluster_events(self, limit: int = 1000) -> List[dict]:
        with self._lock:
            return list(self.cluster_events)[-limit:]
