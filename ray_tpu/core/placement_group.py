"""Public placement-group API.

Analog of ``python/ray/util/placement_group.py`` (:145) in the reference:
atomic gang reservation of resource bundles across nodes with
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD strategies, consumed by tasks/actors
via ``PlacementGroupSchedulingStrategy``. The TPU-specific idiom: one bundle
per pod-slice host with ``{"TPU": chips_per_host, "CPU": ...}`` and
STRICT_SPREAD, giving JAX gang scheduling (one worker process per host).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str = "PACK", name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self, timeout: Optional[float] = None) -> bool:
        from .runtime import get_current_runtime

        rt = get_current_runtime()
        return rt.placement_group_op("ready", self.id,
                                     timeout if timeout is not None else 3600.0)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout_seconds)

    def state(self) -> Optional[dict]:
        from .runtime import get_current_runtime

        return get_current_runtime().placement_group_op("state", self.id)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy, self.name))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None) -> PlacementGroup:
    from .runtime import get_current_runtime

    rt = get_current_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    pg_id = rt.create_placement_group(bundles, strategy, name)
    return PlacementGroup(pg_id, bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    from .runtime import get_current_runtime

    get_current_runtime().placement_group_op("remove", pg.id)


def placement_group_table(pg: Optional[PlacementGroup] = None):
    from .runtime import get_current_runtime

    rt = get_current_runtime()
    if pg is not None:
        return rt.placement_group_op("state", pg.id)
    return None
