"""Resource model with TPU unit instances.

Analog of ``src/ray/common/scheduling/cluster_resource_data.h`` and
``fixed_point.h`` in the reference: resource quantities are fixed-point
(1/10000 granularity) so fractional resources compare exactly; resources named
in ``Config.unit_instance_resources`` (TPU, GPU, ...) are tracked as *unit
instances* — each whole unit is an indexable device slot, so a task asking for
``num_tpus=4`` is bound to concrete chip indices and gets
``TPU_VISIBLE_CHIPS``-style isolation (reference: accelerators/tpu.py:155-195).
"""

from __future__ import annotations

from typing import Dict, List, Optional

GRANULARITY = 10_000  # fixed-point denominator (reference fixed_point.h)


def to_fixed(v: float) -> int:
    return int(round(v * GRANULARITY))


def from_fixed(v: int) -> float:
    return v / GRANULARITY


class ResourceSet:
    """A bag of named fixed-point resource quantities."""

    __slots__ = ("_map",)

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        self._map: Dict[str, int] = {}
        if resources:
            for k, v in resources.items():
                if v:
                    self._map[k] = to_fixed(v)

    @classmethod
    def _from_fixed_map(cls, m: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._map = dict(m)
        return rs

    def get(self, name: str) -> float:
        return from_fixed(self._map.get(name, 0))

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._map.items()}

    def is_empty(self) -> bool:
        return not any(self._map.values())

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._map.get(k, 0) >= v for k, v in self._map.items())

    def __iter__(self):
        return iter(self._map.items())

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._map == other._map


class NodeResources:
    """Total + available resources of one node, with unit-instance tracking.

    Reference: NodeResources / LocalResourceManager instance-level accounting
    (``local_resource_manager.h``). Unit-instance resources also carry a free
    list of device indices so leases bind to concrete chips.
    """

    def __init__(self, total: Dict[str, float], unit_instance_names=("TPU", "GPU")):
        self.total = ResourceSet(total)
        self.available: Dict[str, int] = {k: v for k, v in self.total}
        self.unit_instance_names = set(unit_instance_names)
        self.free_instances: Dict[str, List[int]] = {}
        self.labels: Dict[str, str] = {}
        for name, fixed_amt in self.total:
            if name in self.unit_instance_names:
                n = int(from_fixed(fixed_amt))
                self.free_instances[name] = list(range(n))

    def can_fit(self, req: ResourceSet) -> bool:
        return all(self.available.get(k, 0) >= v for k, v in req)

    def utilization(self) -> float:
        """Critical-resource utilization in [0,1] (for the hybrid policy)."""
        utils = []
        for name, tot in self.total:
            if tot <= 0:
                continue
            avail = self.available.get(name, 0)
            utils.append(1.0 - avail / tot)
        return max(utils) if utils else 0.0

    def allocate(self, req: ResourceSet) -> Optional[Dict[str, List[int]]]:
        """Acquire; returns {resource: [instance indices]} for unit resources,
        or None if it doesn't fit. Fractional requests of unit resources
        (e.g. 0.5 TPU) share instance 0-style binding like the reference."""
        if not self.can_fit(req):
            return None
        binding: Dict[str, List[int]] = {}
        for name, amt in req:
            self.available[name] = self.available.get(name, 0) - amt
            if name in self.free_instances:
                whole = int(from_fixed(amt))
                if whole > 0:
                    idxs = self.free_instances[name][:whole]
                    self.free_instances[name] = self.free_instances[name][whole:]
                    binding[name] = idxs
                else:
                    # fractional: share the LAST free instance (whole-unit
                    # acquires pop from the front, minimizing collisions;
                    # per-instance fractional accounting is a TODO)
                    binding[name] = self.free_instances[name][-1:]
        return binding

    def release(self, req: ResourceSet, binding: Optional[Dict[str, List[int]]] = None):
        for name, amt in req:
            self.available[name] = self.available.get(name, 0) + amt
            if binding and name in binding and int(from_fixed(amt)) > 0:
                self.free_instances[name] = sorted(
                    self.free_instances.get(name, []) + binding[name]
                )

    def view(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self.available.items()}


def parse_task_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    memory: Optional[int] = None,
    default_num_cpus: float = 1.0,
) -> ResourceSet:
    """Merge per-task options into a ResourceSet (reference: ray_option_utils.py)."""
    out: Dict[str, float] = {}
    out["CPU"] = default_num_cpus if num_cpus is None else num_cpus
    if num_tpus:
        out["TPU"] = num_tpus
    if num_gpus:
        out["GPU"] = num_gpus
    if memory:
        out["memory"] = float(memory)
    if resources:
        for k, v in resources.items():
            if k in ("CPU", "TPU", "GPU"):
                raise ValueError(f"Use num_cpus/num_tpus/num_gpus for {k}")
            out[k] = v
    return ResourceSet(out)
