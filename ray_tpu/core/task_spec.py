"""Task specification — the unit shipped from caller to executor.

Analog of the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h:247``): function descriptor, serialized
args (small args inline, large args promoted to the shared store and passed by
reference — reference: core_worker.cc:2166 + ray_config_def.h:199), resource
demand, retry policy, actor linkage, and scheduling strategy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID
from .resources import ResourceSet


@dataclass
class SchedulingStrategy:
    """DEFAULT | SPREAD | node-affinity | placement group (reference:
    python/ray/util/scheduling_strategies.py:15,41,135)."""

    kind: str = "DEFAULT"
    node_id: Optional[bytes] = None  # node affinity
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function_id: str  # key into the GCS function table
    function_name: str
    # each arg: ("v", bytes) inline serialized | ("ref", ObjectID)
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: Dict[str, Tuple[str, Any]] = field(default_factory=dict)
    num_returns: int = 1
    streaming: bool = False  # generator task: yields stream via for_stream ids
    resources: ResourceSet = field(default_factory=ResourceSet)
    # actor creation: the subset of `resources` held for the actor's
    # LIFETIME; the remainder (the implicit 1 scheduling CPU — reference:
    # actors need 1 CPU to schedule, 0 while alive) returns to the node
    # once creation succeeds. None = retain everything.
    retained_resources: Optional[ResourceSet] = None
    max_retries: int = 3
    retry_exceptions: bool = False
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Optional[dict] = None

    # actor linkage
    actor_id: Optional[ActorID] = None  # actor task -> target actor
    is_actor_creation: bool = False
    actor_max_concurrency: int = 1
    actor_is_async: bool = False
    concurrency_group: str = ""
    # direct actor path: per-(owner, actor) submission sequence number and
    # the owner's cached location of the actor (routing hint; stale values
    # bounce back as ActorMissingError and the owner re-resolves)
    actor_seq: int = 0
    actor_node_hex: Optional[str] = None

    # args promoted to the store for this call; pinned until the task settles
    pinned_args: List[ObjectID] = field(default_factory=list)

    # bookkeeping
    attempt: int = 0
    submitted_at: float = field(default_factory=time.time)
    owner_is_driver: bool = True
    # direct (head-bypass) path: number of node-to-node spillback hops this
    # spec has taken; capped at 1 so forwarding can never ping-pong
    direct_hops: int = 0
    # direct path, ref args: owner-side resolution hints shipped with the
    # spec (reference: dependency_resolver.h resolves at the submitter).
    # oid -> ("inline", payload, is_err) for small owned results, or
    # ("node", node_hex) locating the store that sealed the object.
    arg_hints: Optional[Dict[ObjectID, tuple]] = None
    # head path: soft scheduling preference for the node holding the
    # task's largest args (reference: lease_policy.h:56)
    locality_hex: Optional[str] = None
    # cross-task trace context (trace_id, span_id) — reference:
    # tracing_helper.py:88 propagates otel context inside the spec
    trace_ctx: Optional[tuple] = None

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]

    def arg_object_ids(self) -> List[ObjectID]:
        out = [v for k, v in self.args if k == "ref"]
        out += [v for k, v in self.kwargs.values() if k == "ref"]
        return out
