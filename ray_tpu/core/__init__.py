"""Core runtime: tasks, actors, objects, scheduling, control plane."""
