"""Serialization: pickle protocol 5 with out-of-band buffers.

Analog of ``python/ray/_private/serialization.py`` in the reference: values are
pickled once with protocol 5; large contiguous buffers (numpy arrays, bytes,
jax host arrays) are extracted out-of-band so the shared-memory object store
can hold them without an extra copy, and readers can reconstruct numpy arrays
zero-copy over the store's memoryview.

Wire format of a sealed object:
    [u32 meta_len][meta pickle][u64 nbuf][u64 len_i ...][buf_0][buf_1]...
ObjectRefs contained in a value are serialized by id (ownership piggybacks on
the driver-side reference table; reference: contained-object-ids tracking).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import numpy as np

# Threading of "which ObjectRefs were found inside this value" — used by the
# caller to pin contained objects (reference: serialization.py contained ids).
_contained_refs_ctx: List[Any] = []


class SerializedObject:
    __slots__ = ("meta", "buffers", "contained_ids")

    def __init__(self, meta: bytes, buffers: List, contained_ids: List):
        self.meta = meta
        self.buffers = buffers
        self.contained_ids = contained_ids

    @property
    def total_bytes(self) -> int:
        return (
            4
            + len(self.meta)
            + 8
            + 8 * len(self.buffers)
            + sum(len(b.raw()) if isinstance(b, pickle.PickleBuffer) else len(b) for b in self.buffers)
        )

    def to_bytes(self) -> bytes:
        out = bytearray()
        self.write_into(out)
        return bytes(out)

    def write_into(self, out) -> None:
        """Append the wire format into a bytearray / writable buffer proxy."""
        for seg in self.iter_segments():
            out += seg

    def iter_segments(self):
        """Writev-style iteration: yields the wire format as a short header
        segment followed by each out-of-band buffer as its own memoryview —
        no concatenation, no intermediate payload copy. Writers (arena
        seals, socket sends) consume the segments directly."""
        bufs = [
            b.raw() if isinstance(b, pickle.PickleBuffer) else memoryview(b)
            for b in self.buffers
        ]
        header = bytearray()
        header += struct.pack("<I", len(self.meta))
        header += self.meta
        header += struct.pack("<Q", len(bufs))
        for b in bufs:
            header += struct.pack("<Q", b.nbytes)
        yield memoryview(header)
        for b in bufs:
            # flatten non-contiguous pickle-5 buffers (rare: sliced arrays)
            yield b if b.contiguous else memoryview(bytes(b))

    def write_into_view(self, view: "memoryview") -> int:
        """Pack the wire format directly into a writable buffer (an arena
        extent): one copy total, payload bytes go straight from the source
        buffers into shared memory. Returns bytes written."""
        flat = view.cast("B") if view.format != "B" else view
        off = 0
        for seg in self.iter_segments():
            n = seg.nbytes
            flat[off:off + n] = seg.cast("B") if seg.format != "B" else seg
            off += n
        return off


class _ByValuePickler(pickle.Pickler):
    """Plain pickle, except functions/classes from ``__main__`` or local
    scopes are captured by value (cloudpickle). Plain pickle serializes
    them BY REFERENCE — which "succeeds" in the driver and then fails (or
    resolves to the wrong object) in workers whose ``__main__`` is
    worker_runtime. Reference: ray vendors cloudpickle wholesale; this
    keeps the fast path for ordinary data."""

    def reducer_override(self, obj):
        import types

        if isinstance(obj, (types.FunctionType, type)):
            mod = getattr(obj, "__module__", None)
            qual = getattr(obj, "__qualname__", "")
            if mod in ("__main__", None) or "<locals>" in qual:
                import cloudpickle

                return (cloudpickle.loads, (cloudpickle.dumps(obj),))
        return NotImplemented


def serialize(value: Any) -> SerializedObject:
    import io

    import cloudpickle

    buffers: List[pickle.PickleBuffer] = []
    contained: List[Any] = []
    _contained_refs_ctx.append(contained)
    try:
        try:
            # fast path: plain C pickle. If the stream references __main__
            # (driver-defined function/class pickled BY REFERENCE — which
            # would resolve against worker_runtime in workers), re-pickle
            # with the by-value override. The byte scan keeps ordinary
            # data on the C path; a false positive just takes the slower
            # correct path.
            meta = pickle.dumps(value, protocol=5,
                                buffer_callback=buffers.append)
            if b"__main__" in meta:
                buffers.clear()
                contained.clear()
                bio = io.BytesIO()
                _ByValuePickler(bio, protocol=5,
                                buffer_callback=buffers.append).dump(value)
                meta = bio.getvalue()
        except (pickle.PicklingError, AttributeError, TypeError):
            buffers.clear()
            contained.clear()
            # local classes / closures / lambdas (reference: ray cloudpickle)
            meta = cloudpickle.dumps(value, protocol=5,
                                     buffer_callback=buffers.append)
    finally:
        _contained_refs_ctx.pop()
    return SerializedObject(meta, buffers, contained)


def deserialize(data) -> Any:
    """Deserialize from bytes/memoryview produced by SerializedObject.

    When ``data`` is a memoryview over shared memory, reconstructed numpy
    arrays alias it (zero-copy) — same contract as plasma's immutable reads.
    """
    view = memoryview(data)
    if not view.readonly:
        view = view.toreadonly()  # sealed objects are immutable (plasma contract)
    (meta_len,) = struct.unpack_from("<I", view, 0)
    off = 4
    meta = view[off : off + meta_len]
    off += meta_len
    (nbuf,) = struct.unpack_from("<Q", view, off)
    off += 8
    lens = struct.unpack_from(f"<{nbuf}Q", view, off)
    off += 8 * nbuf
    bufs = []
    for ln in lens:
        bufs.append(view[off : off + ln])
        off += ln
    return pickle.loads(bytes(meta) if not isinstance(meta, bytes) else meta, buffers=bufs)


def dumps(value: Any) -> bytes:
    return serialize(value).to_bytes()


loads = deserialize


def note_contained_ref(ref) -> None:
    if _contained_refs_ctx:
        _contained_refs_ctx[-1].append(ref)


def is_zero_copy_type(value: Any) -> bool:
    """True if the value serializes with a dominant out-of-band buffer."""
    return isinstance(value, np.ndarray) and value.dtype != object
