"""Actor API: @remote classes, handles, method calls.

Analog of ``python/ray/actor.py`` in the reference: ``ActorClass.remote()``
registers the class payload, submits an actor-creation task (scheduled with
the actor's lifetime resources — reference: gcs_actor_scheduler), and returns
a serializable ``ActorHandle``. Method calls become ordered actor tasks routed
directly to the actor's dedicated worker (reference:
transport/actor_task_submitter.cc; ordering preserved by the FIFO channel).
Supports named/detached actors, max_restarts/max_task_retries fault
tolerance, max_concurrency thread pools, and asyncio actors.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Any, Dict, Optional

import cloudpickle

from .ids import ActorID
from .remote_function import prepare_args, resolve_scheduling_strategy
from .runtime_env import pack_runtime_env
from .resources import parse_task_resources
from .task_spec import TaskSpec


def _class_id(payload: bytes) -> str:
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, **overrides) -> "ActorMethod":
        m = ActorMethod(self._handle, self._name,
                        overrides.get("num_returns", self._num_returns))
        return m

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(self._name, args, kwargs,
                                           self._num_returns)

    def bind(self, *args):
        """Author a compiled-graph node (reference: dag_node.py bind)."""
        from ray_tpu.dag import _bind

        return _bind(self, *args)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._name}() cannot be called directly; "
            f"use .{self._name}.remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 method_num_returns: Optional[Dict[str, int]] = None,
                 max_task_retries: int = 0):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_class_name", class_name)
        object.__setattr__(self, "_method_num_returns", method_num_returns or {})
        object.__setattr__(self, "_max_task_retries", max_task_retries)

    def __getattr__(self, name: str):
        if (name.startswith("__") and name.endswith("__")
                and name not in ("__ray_terminate__", "__collective_init__",
                                 "__compiled_exec__", "__compiled_setup__",
                                 "__compiled_poison__")):
            raise AttributeError(name)
        return ActorMethod(self, name, self._method_num_returns.get(name, 1))

    def _submit_method(self, method_name: str, args, kwargs, num_returns):
        from .runtime import get_current_runtime

        runtime = get_current_runtime()
        if runtime is None:
            raise RuntimeError("ray_tpu.init() has not been called")
        streaming = num_returns in ("streaming", "dynamic")
        if streaming:
            num_returns = 1
        out_args, out_kwargs, keepalive = prepare_args(runtime, args, kwargs)
        spec = TaskSpec(
            task_id=runtime.next_task_id(),
            job_id=runtime.runtime_context()["job_id"],
            function_id="",
            function_name=f"{self._class_name}.{method_name}",
            args=out_args,
            kwargs=out_kwargs,
            num_returns=num_returns,
            streaming=streaming,
            resources=parse_task_resources(num_cpus=0, default_num_cpus=0.0),
            # actor-task retries follow the actor's max_task_retries
            # (reference: ray_option_utils max_task_retries semantics)
            max_retries=self._max_task_retries,
            actor_id=self._actor_id,
            pinned_args=[r.id for r in keepalive],
        )
        from ray_tpu.util.tracing import current_context

        spec.trace_ctx = current_context()
        refs = runtime.actor_method_call(spec)
        if streaming:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, refs[0])
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_num_returns,
                              self._max_task_retries))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        # deferred to first .remote() — see RemoteFunction.__init__ for why
        self._payload: Optional[bytes] = None
        self._class_id: Optional[str] = None
        self._registered_with = None
        self.__name__ = cls.__name__
        # async actor iff any public method is a coroutine function
        self._is_async = any(
            asyncio.iscoroutinefunction(getattr(cls, m))
            for m in dir(cls)
            if not m.startswith("_") and callable(getattr(cls, m, None))
        )
        self._method_num_returns = {
            m: getattr(getattr(cls, m), "__ray_num_returns__")
            for m in dir(cls)
            if hasattr(getattr(cls, m, None), "__ray_num_returns__")
        }

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        clone = ActorClass.__new__(ActorClass)
        clone.__dict__.update(self.__dict__)
        clone._options = merged
        return clone

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        from .runtime import get_current_runtime
        import pickle

        runtime = get_current_runtime()
        if runtime is None:
            raise RuntimeError("ray_tpu.init() has not been called")
        if self._payload is None:
            self._payload = cloudpickle.dumps(self._cls)
            self._class_id = _class_id(self._payload)
        if self._registered_with is not runtime:
            runtime.register_function(self._class_id, self._payload)
            self._registered_with = runtime
        opt = self._options
        actor_id = ActorID.from_random()
        out_args, out_kwargs, keepalive = prepare_args(runtime, args, kwargs)
        explicit_cpus = opt.get("num_cpus")
        num_cpus = explicit_cpus
        if num_cpus is None:
            # reference semantics (ray_option_utils actor defaults): 1 CPU
            # to *schedule* the creation, 0 CPUs held while alive — the
            # implicit CPU is returned once __init__ succeeds (see
            # retained_resources below)
            num_cpus = 1 if not (opt.get("num_tpus") or opt.get("num_gpus")
                                 or opt.get("resources")) else 0
        spec = TaskSpec(
            task_id=runtime.next_task_id(),
            job_id=runtime.runtime_context()["job_id"],
            function_id=self._class_id,
            function_name=f"{self.__name__}.__init__",
            args=out_args,
            kwargs=out_kwargs,
            num_returns=1,
            resources=parse_task_resources(
                num_cpus=num_cpus,
                num_tpus=opt.get("num_tpus"),
                num_gpus=opt.get("num_gpus"),
                resources=opt.get("resources"),
                memory=opt.get("memory"),
                default_num_cpus=1.0,
            ),
            # lifetime reservation: only EXPLICIT asks persist — the
            # implicit scheduling CPU returns after creation
            retained_resources=parse_task_resources(
                num_cpus=explicit_cpus if explicit_cpus is not None else 0,
                num_tpus=opt.get("num_tpus"),
                num_gpus=opt.get("num_gpus"),
                resources=opt.get("resources"),
                memory=opt.get("memory"),
                default_num_cpus=0.0,
            ),
            max_retries=0,
            scheduling_strategy=resolve_scheduling_strategy(
                opt.get("scheduling_strategy")),
            runtime_env=pack_runtime_env(opt.get("runtime_env"), runtime),
            actor_id=actor_id,
            is_actor_creation=True,
            actor_max_concurrency=opt.get("max_concurrency", 1),
            actor_is_async=self._is_async,
            pinned_args=[r.id for r in keepalive],
        )
        name = opt.get("name")
        namespace = opt.get("namespace", "default")
        max_restarts = opt.get("max_restarts", 0)
        detached = opt.get("lifetime") == "detached"
        max_task_retries = opt.get("max_task_retries", 0)
        if hasattr(runtime, "create_actor_record"):
            runtime.create_actor_record(spec, name, namespace, max_restarts,
                                        detached, max_task_retries)
        else:
            runtime.rpc.call(
                "rpc", "create_actor",
                pickle.dumps((spec, name, namespace, max_restarts, detached,
                              max_task_retries)))
        return ActorHandle(actor_id, self.__name__, self._method_num_returns,
                           max_task_retries)


def method(num_returns: int = 1):
    """Decorator for actor methods with multiple returns (reference:
    python/ray/actor.py ``@ray.method``)."""

    def deco(fn):
        fn.__ray_num_returns__ = num_returns
        return fn

    return deco
