"""Local mode: the whole API surface executed inline in the driver.

Reference: ``ray.init(local_mode=True)``
(python/ray/_private/worker.py LOCAL_MODE) — tasks run synchronously in
the calling process at ``.remote()`` time, actors are plain in-process
objects, and objects live in a dict. No workers, no scheduler, no
subprocesses: breakpoints and stack traces behave like ordinary Python,
which is the entire point. Semantics preserved: results arrive as
ObjectRefs, exceptions re-raise at ``get()``, streaming generators yield
per-item refs, named actors resolve, kv works.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from . import serialization
from .exceptions import ActorDiedError, GetTimeoutError, TaskError
from .ids import ActorID, JobID, ObjectID, TaskID


class _StoredError:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class LocalModeRuntime:
    def __init__(self, namespace: str = "default"):
        self.job_id = JobID.from_random()
        self._driver_task_id = TaskID.for_driver_task(self.job_id)
        self._objects: Dict[ObjectID, Any] = {}
        self._functions: Dict[str, bytes] = {}
        self._fn_cache: Dict[str, Any] = {}
        self._actors: Dict[ActorID, Any] = {}
        self._dead_actors: set = set()
        self._named: Dict[tuple, ActorID] = {}
        self._actor_meta: Dict[ActorID, dict] = {}
        self._streams: Dict[TaskID, dict] = {}
        self._kv: Dict[tuple, bytes] = {}
        self._namespace = namespace
        self._put_counter = 0
        self._lock = threading.RLock()

    # ---- identity ---------------------------------------------------------
    @property
    def mode(self) -> str:
        return "LOCAL"

    def is_initialized(self) -> bool:
        return True

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    def runtime_context(self) -> dict:
        return {
            "job_id": self.job_id, "node_id": "local",
            "worker_id": b"local-driver", "task_id": self._driver_task_id,
            "actor_id": None, "accelerator_ids": {}, "mode": "LOCAL",
        }

    # ---- objects ----------------------------------------------------------
    def put(self, value: Any, _owner=None):
        from .object_ref import ObjectRef

        with self._lock:
            self._put_counter += 1
            oid = ObjectID.for_put(self._driver_task_id, self._put_counter)
            self._objects[oid] = value
        return ObjectRef(oid)

    def get(self, refs, timeout: Optional[float] = None) -> List[Any]:
        out = []
        for r in refs:
            with self._lock:
                if r.id not in self._objects:
                    raise GetTimeoutError(
                        f"local mode: object {r.id.hex()} was never "
                        f"produced")
                v = self._objects[r.id]
            if isinstance(v, _StoredError):
                raise v.exc
            out.append(v)
        return out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        with self._lock:
            ready = [r for r in refs if r.id in self._objects]
        return ready[:num_returns], [r for r in refs
                                     if r not in ready[:num_returns]]

    # ---- functions --------------------------------------------------------
    def register_function(self, function_id: str, payload: bytes) -> None:
        self._functions[function_id] = payload

    def get_function(self, function_id: str):
        import pickle

        if function_id not in self._fn_cache:
            self._fn_cache[function_id] = pickle.loads(
                self._functions[function_id])
        return self._fn_cache[function_id]

    # ---- execution --------------------------------------------------------
    def _resolve(self, packed):
        kind, payload = packed
        if kind == "ref":
            with self._lock:
                if payload not in self._objects:
                    raise GetTimeoutError(
                        f"local mode: arg object {payload.hex()} was never "
                        f"produced")
                v = self._objects[payload]
            if isinstance(v, _StoredError):
                raise v.exc
            return v
        return serialization.deserialize(payload)

    def _resolve_args(self, spec):
        args = [self._resolve(a) for a in spec.args]
        kwargs = {k: self._resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    def _store_results(self, spec, value) -> list:
        from .object_ref import ObjectRef

        rids = spec.return_ids()
        with self._lock:
            if spec.num_returns == 0:
                pass
            elif spec.num_returns == 1:
                self._objects[rids[0]] = value
            else:
                vals = list(value)
                if len(vals) != spec.num_returns:
                    raise TaskError(
                        spec.function_name,
                        f"task returned {len(vals)} values, expected "
                        f"num_returns={spec.num_returns}")
                for oid, v in zip(rids, vals):
                    self._objects[oid] = v
        return [ObjectRef(oid) for oid in rids]

    def _execute(self, spec, fn_thunk) -> list:
        """Run a task or actor method inline; store results or the error.
        ``fn_thunk`` resolves the callable INSIDE the try so lookup errors
        (missing method, dead class) defer to get() like every other
        failure. The one execution body (tasks and actor methods must not
        drift)."""
        import inspect

        if spec.streaming:
            # record exists before anything can fail, so a pre-iteration
            # error surfaces as ("error",) — not a silently empty stream
            with self._lock:
                rec = self._streams[spec.task_id] = {
                    "items": [], "done": False, "error": False}
        try:
            fn = fn_thunk()
            args, kwargs = self._resolve_args(spec)
            if spec.streaming:
                items = rec["items"]
                try:
                    for i, item in enumerate(fn(*args, **kwargs)):
                        oid = ObjectID.for_stream(spec.task_id, i)
                        with self._lock:
                            self._objects[oid] = item
                            items.append(oid)
                finally:
                    rec["done"] = True
                return self._store_results(spec, len(items))
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)  # loop closed deterministically
            return self._store_results(spec, result)
        except BaseException as e:  # noqa: BLE001
            if spec.streaming:
                rec["error"] = True
                rec["done"] = True
            return self._store_err(spec, e)

    def submit_task(self, spec) -> list:
        return self._execute(spec,
                             lambda: self.get_function(spec.function_id))

    def _store_err(self, spec, e) -> list:
        from .object_ref import ObjectRef

        import traceback

        err = e if isinstance(e, (TaskError, ActorDiedError)) else TaskError(
            spec.function_name, traceback.format_exc(), cause=e)
        with self._lock:
            for oid in spec.return_ids():
                self._objects[oid] = _StoredError(err)
        return [ObjectRef(oid) for oid in spec.return_ids()]

    def stream_next(self, task_id, index: int, timeout=None, owner=None):
        with self._lock:
            rec = self._streams.get(task_id)
            if rec is None:
                return ("end",)
            if index < len(rec["items"]):
                return ("item", rec["items"][index])
            if rec.get("error"):
                return ("error",)  # consumer re-raises via the primary ref
            return ("end",) if rec["done"] else ("wait",)

    # ---- actors -----------------------------------------------------------
    def create_actor_record(self, spec, name, namespace, max_restarts,
                            detached, max_task_retries=0) -> None:
        with self._lock:
            if name and (namespace, name) in self._named:
                raise ValueError(
                    f"actor name {name!r} already taken in namespace "
                    f"{namespace!r}")
        cls = self.get_function(spec.function_id)
        try:
            args, kwargs = self._resolve_args(spec)
            instance = cls(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            # cluster parity: a failing __init__ surfaces as ActorDiedError
            # at the first method-result get(), not at .remote()
            with self._lock:
                self._dead_actors.add(spec.actor_id)
                self._actor_meta[spec.actor_id] = {
                    "class_name": getattr(cls, "__name__", "Actor"),
                    "name": None, "namespace": namespace,
                    "creation_error": repr(e),
                }
            return
        with self._lock:
            self._actors[spec.actor_id] = instance
            self._actor_meta[spec.actor_id] = {
                "class_name": getattr(cls, "__name__", "Actor"),
                "name": name, "namespace": namespace,
            }
            if name:
                self._named[(namespace, name)] = spec.actor_id

    def actor_method_call(self, spec) -> list:
        with self._lock:
            instance = self._actors.get(spec.actor_id)
            meta = self._actor_meta.get(spec.actor_id, {})
        if instance is None:
            cause = meta.get("creation_error") or "actor is dead"
            if spec.streaming:
                # the stream must surface ("error",), not iterate empty
                with self._lock:
                    self._streams[spec.task_id] = {
                        "items": [], "done": True, "error": True}
            return self._store_err(
                spec, ActorDiedError(spec.actor_id, cause))
        method_name = spec.function_name.rsplit(".", 1)[-1]
        return self._execute(spec,
                             lambda: getattr(instance, method_name))

    def get_actor_info(self, name: str, namespace: str):
        with self._lock:
            aid = self._named.get((namespace, name))
            if aid is None or aid in self._dead_actors:
                return None
            meta = self._actor_meta[aid]
        return {"actor_id": aid, "class_name": meta["class_name"],
                "max_task_retries": 0}

    def kill_actor(self, actor_id, no_restart: bool = True):
        with self._lock:
            self._actors.pop(actor_id, None)
            self._dead_actors.add(actor_id)
            for k, v in list(self._named.items()):
                if v == actor_id:
                    del self._named[k]

    def cancel_task(self, oid, force: bool = False):
        pass  # tasks already ran inline; nothing in flight to cancel

    # ---- refs: no-ops (everything lives until shutdown) -------------------
    def add_local_ref(self, oid) -> None:
        pass

    def remove_local_ref(self, oid) -> None:
        pass

    def add_borrow_ref(self, oid) -> None:
        pass

    # ---- cluster info -----------------------------------------------------
    def kv(self, op: str, *args):
        if op == "put":
            key, value = args[0], args[1]
            ns = args[2] if len(args) > 2 else "default"
            self._kv[(ns, key)] = value
            return True
        if op == "get":
            key = args[0]
            ns = args[1] if len(args) > 1 else "default"
            return self._kv.get((ns, key))
        if op == "del":
            key = args[0]
            ns = args[1] if len(args) > 1 else "default"
            return self._kv.pop((ns, key), None) is not None
        if op == "keys":
            prefix = args[0]
            ns = args[1] if len(args) > 1 else "default"
            return [k for (n, k) in self._kv if n == ns
                    and k.startswith(prefix)]
        if op == "exists":
            key = args[0]
            ns = args[1] if len(args) > 1 else "default"
            return (ns, key) in self._kv
        raise ValueError(f"unknown kv op {op!r}")

    def available_resources(self):
        import os

        return {"CPU": float(os.cpu_count() or 1)}

    def cluster_resources(self):
        return self.available_resources()

    def nodes(self):
        return [{"NodeID": "local", "Alive": True,
                 "Resources": self.cluster_resources(), "Labels": {}}]

    def create_placement_group(self, bundles, strategy, name=""):
        from .ids import PlacementGroupID

        return PlacementGroupID.from_random()

    def placement_group_op(self, op: str, *args):
        if op == "ready" or op == "wait":
            return True
        return None

    def state_list(self, kind: str, limit: int = 1000):
        if kind == "nodes":
            return [{"node_id": "local", "alive": True,
                     "resources": self.cluster_resources(), "labels": {}}]
        return []

    def disconnect(self) -> None:
        with self._lock:
            self._objects.clear()
            self._actors.clear()
