"""ObjectRef — the client-side future handle.

Analog of the reference's ``ray.ObjectRef`` (Cython, _raylet.pyx): a handle to
an immutable object somewhere in the cluster. Refs are serializable (they
travel inside task args and other objects); deserializing registers a borrow
with the owner via the contained-ids mechanism in serialization.py
(reference: reference_count.h borrower bookkeeping).
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from . import ref_tracker, serialization
from .ids import ObjectID

# Installed by the runtime (driver api or worker runtime) so that refs can
# resolve `.get()`/release without importing the runtime module (avoids cycle).
_runtime = None

# Deferred ref-drop queue. ``ObjectRef.__del__`` runs inside the garbage
# collector, which can fire on ANY allocation — including one made while a
# runtime thread holds a non-reentrant lock (DirectTaskManager._lock, node
# locks). Calling ``remove_local_ref`` synchronously from __del__ therefore
# self-deadlocks that thread (observed: the direct-path completion thread
# wedged inside complete(), losing a stream's EOF forever — the
# test_stream_empty full-suite hang). __del__ only appends to this deque
# (atomic, lock-free); a dedicated reaper thread drains it, so ref releases
# always run on a thread that holds no runtime locks. The reference solves
# the same problem the same way (_raylet's deferred ref-release queue).
_drop_queue: "collections.deque" = collections.deque()
_drop_event = threading.Event()
_reaper_started = False
_reaper_lock = threading.Lock()


def _reaper_loop() -> None:
    while True:
        _drop_event.wait()
        _drop_event.clear()
        while True:
            try:
                oid = _drop_queue.popleft()
            except IndexError:
                break
            rt = _runtime
            if rt is None:
                continue  # runtime torn down: nothing left to release
            try:
                rt.remove_local_ref(oid)
            except Exception:
                pass  # shutdown race / head gone


def _ensure_reaper() -> None:
    global _reaper_started
    if _reaper_started:
        return
    with _reaper_lock:
        if not _reaper_started:
            threading.Thread(target=_reaper_loop, daemon=True,
                             name="ref-reaper").start()
            _reaper_started = True


def set_runtime(rt) -> None:
    global _runtime
    if rt is None:
        # cluster shutdown: drops for the old runtime are void
        _drop_queue.clear()
    else:
        _ensure_reaper()
    _runtime = rt


def get_runtime():
    return _runtime


def flush_pending_drops(timeout: float = 1.0) -> None:
    """Best-effort wait for queued __del__ ref drops to apply (tests)."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while _drop_queue and _time.monotonic() < deadline:
        _drop_event.set()
        _time.sleep(0.005)


class ObjectRef:
    __slots__ = ("id", "owner_node", "_weak")

    def __init__(self, oid: ObjectID, owner_node: Optional[bytes] = None, _register: bool = True):
        self.id = oid
        self.owner_node = owner_node
        self._weak = not _register
        if _register and _runtime is not None:
            _runtime.add_local_ref(oid)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def future(self):
        import concurrent.futures

        fut = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_runtime.get([self], timeout=None)[0])
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, lambda: _runtime.get([self], timeout=None)[0])
        return fut.__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        serialization.note_contained_ref(self)
        if _runtime is not None:
            _runtime.add_borrow_ref(self.id)
        return (_deserialize_ref, (self.id, self.owner_node))

    def __del__(self):
        # NEVER release synchronously: __del__ runs inside the GC, which
        # can fire on a thread holding runtime locks — hand the drop to
        # the reaper thread instead (see _drop_queue above)
        if not self._weak and _runtime is not None:
            try:
                _drop_queue.append(self.id)
                _drop_event.set()
            except Exception:  # interpreter shutdown
                pass


def _deserialize_ref(oid: ObjectID, owner_node):
    ref = ObjectRef(oid, owner_node)
    # a deserialized handle is a BORROW: this process holds but does not
    # own it (reference: reference_count.h borrower bookkeeping)
    ref_tracker.note_borrow(oid)
    return ref


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded objects.

    Analog of the reference's ObjectRefGenerator
    (python/ray/_raylet.pyx:1074-1317 streaming generators): ``__next__``
    returns the next yielded item's ObjectRef, blocking until the producer
    seals it; StopIteration once the producer finished and all items were
    consumed; a failed producer raises its error (stored on the primary
    return) at the point of failure.

    ``owner`` is the stream's owner route, stamped when the handle leaves
    the owning process: ``("d", head_node_hex)`` for a driver-owned
    stream, ``("w", node_hex, worker_id)`` for a worker-owned one, None
    for head-path streams (the head keeps their records). Consumers in
    other processes subscribe to the OWNER over this route
    (``stream_sub``) and pull item payloads peer-to-peer — the head never
    sees steady-state stream traffic.
    """

    def __init__(self, task_id, primary_ref: ObjectRef, owner=None):
        self._task_id = task_id
        self._primary = primary_ref
        self._owner = tuple(owner) if owner else None
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        rt = get_runtime()
        while True:
            rep = rt.stream_next(self._task_id, self._i, timeout=2.0,
                                 owner=self._owner)
            kind = rep[0]
            if kind == "item":
                self._i += 1
                # rep[2] (when present) is a location hint: the node whose
                # store holds the item's bytes — the consumer's get pulls
                # peer-to-peer instead of asking the directory
                hint = rep[2] if len(rep) > 2 else None
                ref = ObjectRef(rep[1], owner_node=hint)
                ref_tracker.annotate(rep[1], ref_tracker.KIND_STREAM_ITEM)
                return ref
            if kind == "end":
                raise StopIteration
            if kind == "error":
                # the error payload is sealed on the primary return
                rt.get([self._primary], timeout=30)
                raise RuntimeError("streaming task failed")  # unreachable
            if kind == "gone":
                from .exceptions import ActorDiedError

                raise ActorDiedError(
                    None, "stream owner died: "
                    + (rep[1] if len(rep) > 1 and rep[1]
                       else "owner process unreachable"))
            # "wait": producer still running

    def __len__(self):
        raise TypeError("streaming generator has no static length")

    def completed(self) -> ObjectRef:
        """Ref that resolves to the total item count when the task ends."""
        return self._primary

    def __reduce__(self):
        # The handle is leaving this process. If WE own the stream
        # (direct path), mark it published — the owner retains the item
        # table and serves subscribers directly — and stamp our owner
        # route into the pickled handle. A borrowed handle re-serialized
        # keeps the original route; head-path streams stay route-less
        # (their consumers use the head's stream records).
        owner = self._owner
        rt = get_runtime()
        if rt is not None and owner is None:
            try:
                if rt.publish_stream(self._task_id):
                    owner = rt.stream_owner_route()
            except Exception:
                pass
        return (ObjectRefGenerator, (self._task_id, self._primary, owner))
