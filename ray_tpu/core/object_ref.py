"""ObjectRef — the client-side future handle.

Analog of the reference's ``ray.ObjectRef`` (Cython, _raylet.pyx): a handle to
an immutable object somewhere in the cluster. Refs are serializable (they
travel inside task args and other objects); deserializing registers a borrow
with the owner via the contained-ids mechanism in serialization.py
(reference: reference_count.h borrower bookkeeping).
"""

from __future__ import annotations

from typing import Optional

from . import serialization
from .ids import ObjectID

# Installed by the runtime (driver api or worker runtime) so that refs can
# resolve `.get()`/release without importing the runtime module (avoids cycle).
_runtime = None


def set_runtime(rt) -> None:
    global _runtime
    _runtime = rt


def get_runtime():
    return _runtime


class ObjectRef:
    __slots__ = ("id", "owner_node", "_weak")

    def __init__(self, oid: ObjectID, owner_node: Optional[bytes] = None, _register: bool = True):
        self.id = oid
        self.owner_node = owner_node
        self._weak = not _register
        if _register and _runtime is not None:
            _runtime.add_local_ref(oid)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def future(self):
        import concurrent.futures

        fut = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_runtime.get([self], timeout=None)[0])
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, lambda: _runtime.get([self], timeout=None)[0])
        return fut.__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        serialization.note_contained_ref(self)
        if _runtime is not None:
            _runtime.add_borrow_ref(self.id)
        return (_deserialize_ref, (self.id, self.owner_node))

    def __del__(self):
        if not self._weak and _runtime is not None:
            try:
                _runtime.remove_local_ref(self.id)
            except Exception:  # interpreter shutdown
                pass


def _deserialize_ref(oid: ObjectID, owner_node):
    return ObjectRef(oid, owner_node)


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded objects.

    Analog of the reference's ObjectRefGenerator
    (python/ray/_raylet.pyx:1074-1317 streaming generators): ``__next__``
    returns the next yielded item's ObjectRef, blocking until the producer
    seals it; StopIteration once the producer finished and all items were
    consumed; a failed producer raises its error (stored on the primary
    return) at the point of failure.
    """

    def __init__(self, task_id, primary_ref: ObjectRef):
        self._task_id = task_id
        self._primary = primary_ref
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        rt = get_runtime()
        while True:
            rep = rt.stream_next(self._task_id, self._i, timeout=2.0)
            kind = rep[0]
            if kind == "item":
                self._i += 1
                return ObjectRef(rep[1])
            if kind == "end":
                raise StopIteration
            if kind == "error":
                # the error payload is sealed on the primary return
                rt.get([self._primary], timeout=30)
                raise RuntimeError("streaming task failed")  # unreachable
            # "wait": producer still running

    def __len__(self):
        raise TypeError("streaming generator has no static length")

    def completed(self) -> ObjectRef:
        """Ref that resolves to the total item count when the task ends."""
        return self._primary

    def __reduce__(self):
        # The handle is leaving this process: a direct-path stream lives
        # only in its owner's buffer, so mirror it to the head first
        # (publish_stream is a no-op for head-path/borrowed streams).
        rt = get_runtime()
        if rt is not None:
            try:
                rt.publish_stream(self._task_id)
            except Exception:
                pass
        return (ObjectRefGenerator, (self._task_id, self._primary))
