"""Direct (head-bypass) task path: owner-side task table + eligibility.

The reference keeps the GCS out of the normal-task hot path entirely: the
submitting CoreWorker owns the task (retries, result table), resolves its
dependencies locally (``src/ray/core_worker/transport/dependency_resolver.h:29``
``LocalDependencyResolver``), leases a worker from its *local* raylet, and
pushes the task directly
(``src/ray/core_worker/transport/normal_task_submitter.cc:355``,
``reference_count.h:61`` — ownership lives with the submitter). Round 2 of
this framework routed every submit/finish through the single Head, capping
throughput at what one GIL-bound process can relay.

This module is the submitter side of the same decentralization: eligible
plain tasks go straight to the submitting process's *node* (worker → its
node over the existing channel; driver → the in-process head node), which
executes them from its own worker pool — or spills them one hop to a peer
node over the daemon↔daemon mesh — and replies directly to the owner.
The head only sees small *batched* event reports (object locations +
observability), amortized hundreds of tasks per message.

Ref args are resolved **owner-side** before submission (the analog of
``LocalDependencyResolver``): args produced by this owner's own direct
tasks resolve in-process (inline payloads ship as hints in the spec; large
results ship the sealing node's hex so the executor pulls peer-to-peer);
external objects are waited on via the object directory, then submitted.
A task never occupies a worker slot while its dependencies are pending.

Ownership semantics match the reference: if the owner dies, its in-flight
direct tasks and their results are lost (Ray's owner-died behavior); if
the executor dies, the owner retries per ``max_retries``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from . import serialization
from .exceptions import TaskCancelledError, WorkerCrashedError
from .ids import ObjectID, TaskID
from .task_spec import TaskSpec

# resources a node can grant from its worker-pool slots without head-side
# accounting (unit-instance resources like TPU need index binding; custom
# resources need cluster placement)
_DIRECT_RESOURCES = {"CPU"}

_SYSTEM_ERRS = ("WorkerCrashedError", "NodeDiedError")

# inline-hint ceiling: small owned results are copied into the spec so the
# executor never touches a store for them (mirrors the inline-arg path)
_INLINE_HINT_MAX = 100 * 1024


# actor-call errors that mean the call NEVER EXECUTED on the target
# (always safe to resubmit after re-resolving the actor's location)
_ACTOR_LOC_ERRS = ("ActorMissingError", "NodeDiedError")
# errors where the call may have started executing (resubmit only per
# max_task_retries, matching the reference's at-most-once default)
_ACTOR_SYS_ERRS = _ACTOR_LOC_ERRS + ("ActorDiedError", "WorkerCrashedError")


def bounded_sub_rounds(call_round: Callable[[float], tuple],
                       timeout: Optional[float]):
    """Consumer-side subscription loop: re-issue one bounded (<=2 s)
    stream_sub round via ``call_round(round_timeout)`` until a non-wait
    reply or the deadline passes — rounds stay short so parked
    subscriptions never pin node/peer threads forever. Shared by the
    worker (rpc round) and driver (head-node route) consumers."""
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    while True:
        remaining = (None if deadline is None
                     else deadline - _time.monotonic())
        round_t = (2.0 if remaining is None
                   else max(0.0, min(remaining, 2.0)))
        rep = call_round(round_t)
        if rep[0] != "wait" or (remaining is not None
                                and remaining <= round_t):
            return rep


def actor_call_eligible(spec: TaskSpec) -> bool:
    """Direct-path test for actor method calls. Streaming generator calls
    are eligible too: their item announcements ride the direct reply
    chain to the owner (``on_stream_item``), so the actor plane is
    uniformly head-free (reference: streaming generator item reports go
    submitter-side, core_worker.h:392 TryReadObjectRefStream). Per-call
    runtime_env is deliberately NOT an exclusion: the actor process's env
    is fixed at creation, so method calls can't change it — and routing
    every call one way keeps per-caller ordering structural."""
    return (spec.actor_id is not None
            and not spec.is_actor_creation)


def direct_eligible(spec: TaskSpec) -> bool:
    """Hot-class test: plain <=1-CPU task, default placement. Ref args are
    fine — the owner resolves them before submission (dependency resolver)
    and the executor pulls via location hints. num_cpus>1 needs real
    resource accounting (a node grants direct tasks one worker SLOT, ~1
    CPU), so it keeps the head path. Streaming tasks are eligible: items
    stream back over the same reply chain as the completion."""
    s = spec.scheduling_strategy
    return (
        spec.actor_id is None
        and not spec.is_actor_creation
        and spec.runtime_env is None
        and s.kind == "DEFAULT"
        and s.placement_group_id is None
        and s.node_id is None
        and all(k in _DIRECT_RESOURCES for k, _ in spec.resources)
        and spec.resources.get("CPU") <= 1.0
    )


class _StreamState:
    """Owner-side bookkeeping for one streaming (generator) task."""

    __slots__ = ("count", "handed", "done", "dropped", "published",
                 "exec_hex")

    def __init__(self):
        self.count = 0                 # items announced so far
        self.handed: set = set()       # item oids returned by stream_next
        self.done: Optional[Tuple[int, bool]] = None  # (total, is_error)
        self.dropped = False           # generator ref released
        # generator handle serialized out of this process: the owner keeps
        # the stream state alive and serves remote subscribers directly
        # (stream_next_remote) — nothing is mirrored to the head
        self.published = False
        # node that executes the generator (every item announcement
        # carries it): the location hint remote subscribers use to pull
        # store-resident item payloads peer-to-peer
        self.exec_hex: Optional[str] = None


class DirectTaskManager:
    """Owner-side table of in-flight direct tasks + their inline results.

    The analog of the reference CoreWorker's ``TaskManager`` + in-process
    memory store + ``LocalDependencyResolver`` (``task_manager.h:208``,
    ``memory_store.cc``, ``dependency_resolver.h:29``): completion wakes
    local getters; system failures retry by resubmitting through the
    ``submit`` callback; user errors deserialize to raised exceptions;
    ref-arg tasks defer until every dependency is available somewhere.

    Optional collaborators (wired by the owning runtime):
      - ``ext_wait(oids, timeout) -> ready_list``: one bounded round of
        availability-checking external (non-owned) objects against the
        cluster object directory.
      - ``on_unpin(oids)``: called (outside the lock) when the last
        in-flight pin on each oid is released at task settle — the
        driver wires deferred head-side deletes through it.
    """

    def __init__(self, submit: Callable[[TaskSpec], None],
                 ext_wait: Optional[Callable] = None,
                 locate: Optional[Callable] = None,
                 on_unpin: Optional[Callable] = None):
        self._submit = submit
        self._ext_wait = ext_wait
        self._on_unpin = on_unpin
        # optional: hex of the node holding a LARGE external object (the
        # locality signal for args this owner didn't produce)
        self._locate = locate
        # wired by DirectActorSubmitter: dep-ready + failure + completion
        # routing for actor-call specs (ordered per-actor submission)
        self._actor_ready_cb: Optional[Callable] = None
        self._actor_failed_cb: Optional[Callable] = None
        self._actor_done_cb: Optional[Callable] = None
        self._actor_cancel_cb: Optional[Callable] = None
        from .lock_debug import tracked_lock

        self._lock = tracked_lock("DirectTaskManager._lock")
        self._cv = threading.Condition(self._lock)
        self._pending: Dict[TaskID, TaskSpec] = {}
        self._cancelled: set = set()
        # ---- owner-side arg pins (reference: reference_count.h submitter
        # pinning). An in-flight task's ref args stay alive on the OWNER'S
        # say-so: _pin_counts is consulted by the owner's delete decisions
        # (holds_pin), holder nodes additionally take a per-task lease
        # from spec.pinned_args (node.py _arg_leases). No head RPCs.
        self._task_pins: Dict[TaskID, tuple] = {}
        self._pin_counts: Dict[ObjectID, int] = {}
        # oids whose ObjectRef died before the task completed: their
        # results are discarded on arrival instead of retained forever
        self._dropped: set = set()
        # oid -> (payload bytes | None, is_error); None payload = large
        # result sealed in the executor node's store (get falls back to the
        # store/locate path)
        self._results: Dict[ObjectID, Tuple[Optional[bytes], bool]] = {}
        # oid -> node hex that sealed a large (store-resident) result;
        # shipped as a pull hint when the oid is a downstream task's arg
        self._result_nodes: Dict[ObjectID, str] = {}
        # ---- lineage (store-resident results only) ---------------------
        # tid -> settled spec retained for reconstruction: a store-sealed
        # result dies with its node, and the owner is the only process
        # that can resubmit the creating task (reference:
        # object_recovery_manager.h:90 RecoverObject + reference_count.cc
        # lineage pinning). Inline results live in _results and need no
        # lineage. Bounded FIFO (direct_lineage_max) — eviction means
        # "not reconstructable", matching the reference's lineage cap.
        self._lineage: "OrderedDict[TaskID, TaskSpec]" = OrderedDict()
        # tid -> store-resident return oids still referenced; when the
        # last one is dropped the lineage entry is released
        self._lineage_live: Dict[TaskID, Set[ObjectID]] = {}
        # streaming generator tasks owned by this manager: items arrive
        # via on_stream_item over the direct reply chain (same FIFO as the
        # final completion), the consumer reads via stream_next — the
        # owner-side replacement for the head's stream records
        self._streams: Dict[TaskID, _StreamState] = {}
        # published streams that reached EOF with their local handle
        # dropped: remote subscribers may still read them, so they are
        # retained — but BOUNDED (FIFO, published_stream_retain_max):
        # eviction purges the oldest, and a straggling subscriber of an
        # evicted stream sees ("gone",). The owner-side analog of the
        # head's old stream-record TTL GC.
        self._published_done: "OrderedDict[TaskID, bool]" = OrderedDict()
        # ---- dependency resolver state ---------------------------------
        # task_id -> set of oids still unavailable; submit fires when empty
        self._deferred: Dict[TaskID, Set[ObjectID]] = {}
        # external (non-owned) oid -> task_ids waiting on it
        self._ext_waiting: Dict[ObjectID, Set[TaskID]] = {}
        self._poller_started = False
        # wait() events set on every completion (mixed-wait integration)
        self._wait_events: set = set()

    def add_waiter(self, event) -> None:
        self._wait_events.add(event)

    def remove_waiter(self, event) -> None:
        self._wait_events.discard(event)

    def _wake_waiters(self) -> None:
        for e in list(self._wait_events):
            e.set()

    # ------------------------------------------------------------ submit

    def register(self, spec: TaskSpec) -> Optional[TaskSpec]:
        """Record ownership; resolve dependencies. Returns the spec when it
        is ready to submit now, or None if it was deferred (the resolver
        submits it when its deps become available)."""
        arg_ids = spec.arg_object_ids()
        with self._lock:
            self._pending[spec.task_id] = spec
            if spec.pinned_args and spec.task_id not in self._task_pins:
                self._task_pins[spec.task_id] = tuple(spec.pinned_args)
                for oid in spec.pinned_args:
                    self._pin_counts[oid] = self._pin_counts.get(oid, 0) + 1
            if not arg_ids:
                return spec
            owned: List[ObjectID] = []
            ext: List[ObjectID] = []
            for oid in arg_ids:
                if oid in self._results:
                    continue  # owned + completed: hint stamped at submit
                if oid.task_id() in self._pending:
                    owned.append(oid)  # owned + still running
                else:
                    ext.append(oid)  # external: availability via directory
            if not owned and not ext:
                self._stamp_hints_locked(spec)
                return spec
        # synchronous availability probe for external deps (outside the
        # lock — the probe takes cluster locks / an RPC): the common case
        # (args already materialized) submits immediately
        if ext and self._ext_wait is not None:
            try:
                ready_now = set(self._ext_wait(list(ext), 0.0))
            except Exception:
                ready_now = set()
            ext = [o for o in ext if o not in ready_now]
        with self._lock:
            # re-check under the lock: owned deps may have completed (or
            # external ones sealed) during the probe window
            missing = {o for o in owned if o not in self._results}
            missing.update(o for o in ext if o not in self._results)
            if not missing:
                self._stamp_hints_locked(spec)
                return spec
            self._deferred[spec.task_id] = missing
            ext_missing = [o for o in ext if o in missing]
            for oid in ext_missing:
                self._ext_waiting.setdefault(oid, set()).add(spec.task_id)
            if ext_missing:
                self._ensure_poller_locked()
        return None

    def _stamp_hints_locked(self, spec: TaskSpec) -> None:
        """Attach resolution + locality hints for the spec's ref args."""
        hints: Dict[ObjectID, tuple] = {}
        for oid in spec.arg_object_ids():
            res = self._results.get(oid)
            if res is not None:
                payload, is_err = res
                if payload is not None and len(payload) <= _INLINE_HINT_MAX:
                    hints[oid] = ("inline", payload, is_err)
                    continue
                node_hex = self._result_nodes.get(oid)
                if node_hex:
                    hints[oid] = ("node", node_hex)
            elif self._locate is not None:
                # external object: the directory knows who holds it (only
                # LARGE objects return a hint — locality is pointless for
                # bytes that fit in the spec)
                try:
                    node_hex = self._locate(oid)
                except Exception:
                    node_hex = None
                if node_hex:
                    hints[oid] = ("node", node_hex)
        if hints:
            spec.arg_hints = hints

    def _ensure_poller_locked(self) -> None:
        if self._poller_started or self._ext_wait is None:
            return
        self._poller_started = True
        threading.Thread(target=self._poll_external, daemon=True,
                         name="direct-dep-poller").start()

    def _poll_external(self) -> None:
        """Availability loop for external deps: one bounded ``ext_wait``
        round over the union of outstanding oids (the directory wait is
        cv-based on the head, so readiness propagates promptly)."""
        while True:
            with self._lock:
                oids = list(self._ext_waiting.keys())
                if not oids:
                    self._poller_started = False
                    return
            try:
                ready = self._ext_wait(oids, 0.2)
            except Exception:
                ready = []
            if ready:
                self.deps_available(ready)

    def deps_available(self, oids) -> None:
        """Mark objects available; submit any deferred spec whose last
        missing dependency this satisfies."""
        to_submit: List[TaskSpec] = []
        actor_ready: List[TaskSpec] = []
        ready_set = set(oids)
        with self._lock:
            for oid in ready_set:
                self._ext_waiting.pop(oid, None)
            for tid, deps in list(self._deferred.items()):
                deps -= ready_set
                if not deps:
                    del self._deferred[tid]
                    spec = self._pending.get(tid)
                    if spec is not None and tid not in self._cancelled:
                        if spec.actor_id is not None:
                            actor_ready.append(spec)  # ordered queue decides
                        else:
                            self._stamp_hints_locked(spec)
                            to_submit.append(spec)
        for spec in to_submit:
            self._submit(spec)
        if actor_ready and self._actor_ready_cb is not None:
            for spec in actor_ready:
                self._actor_ready_cb(spec)

    def cancel(self, oid: ObjectID) -> bool:
        """Owner-side cancel: mark so the (already-running) result seals
        TaskCancelledError on arrival; a still-deferred task is cancelled
        entirely owner-side. Returns True if it was pending."""
        sealed_spec = None
        with self._lock:
            tid = oid.task_id()
            if tid not in self._pending:
                return False
            self._cancelled.add(tid)
            if tid in self._deferred:
                # never submitted: settle in place
                del self._deferred[tid]
                for waiters in self._ext_waiting.values():
                    waiters.discard(tid)
                sealed_spec = self._pending.pop(tid)
                self._cancelled.discard(tid)
                err = TaskCancelledError(f"task {tid.hex()} cancelled")
                payload = serialization.serialize(err).to_bytes()
                for roid in sealed_spec.return_ids():
                    self._results[roid] = (payload, True)
                if sealed_spec.streaming:
                    self._settle_stream_locked(sealed_spec, True)
                self._cv.notify_all()
        if sealed_spec is not None:
            self._wake_waiters()
            self._release_pins(sealed_spec)
            if (sealed_spec.actor_id is not None
                    and self._actor_cancel_cb is not None):
                # unwedge the actor route: the cancelled call must leave
                # the ordered queue or every later call stays blocked
                self._actor_cancel_cb(sealed_spec)
            # downstream tasks deferred on this task's returns must wake
            # (they will run and raise the sealed TaskCancelledError)
            self.deps_available(sealed_spec.return_ids())
        return True

    def _release_pins(self, spec: TaskSpec) -> None:
        """Release this task's arg pins (settle path); fires ``on_unpin``
        for oids whose last pin dropped so the owner can apply any
        deferred delete."""
        released: List[ObjectID] = []
        with self._lock:
            oids = self._task_pins.pop(spec.task_id, None)
            if oids:
                for oid in oids:
                    n = self._pin_counts.get(oid, 0) - 1
                    if n <= 0:
                        self._pin_counts.pop(oid, None)
                        released.append(oid)
                    else:
                        self._pin_counts[oid] = n
        if released and self._on_unpin is not None:
            try:
                self._on_unpin(released)
            except Exception:
                pass

    def holds_pin(self, oid: ObjectID) -> bool:
        """True while an in-flight task owned here pins ``oid`` (the
        owner's delete decisions consult this instead of head pins)."""
        with self._lock:
            return oid in self._pin_counts

    def pin_counts(self) -> Dict[ObjectID, int]:
        """Snapshot of live in-flight arg pins (memory observability)."""
        with self._lock:
            return dict(self._pin_counts)

    # ------------------------------------------------------------ complete

    def complete(self, task_id: TaskID, err_name: Optional[str],
                 results: List[Tuple[ObjectID, Optional[bytes], bool]],
                 exec_hex: Optional[str] = None) -> None:
        """Executor reply. ``results`` entries: (oid, inline payload | None
        for store-sealed, is_error); ``exec_hex`` = node that sealed
        store-resident results (pull hint for dependents)."""
        resubmit = None
        settled_spec = None
        actor_handoff = None
        sealed_oids: List[ObjectID] = []
        with self._lock:
            spec = self._pending.get(task_id)
            if spec is None:
                # Stale (superseded attempt / duplicate delivery) — but EOF
                # delivery must stay idempotent: if a stream exists whose
                # EOF never landed (a lost/reordered first delivery), settle
                # it now so no consumer blocks in stream_next forever (an
                # empty stream's ONLY signal is the EOF).
                st = self._streams.get(task_id)
                if st is not None and st.done is None:
                    st.done = (st.count, err_name is not None)
                    self._cv.notify_all()
                return
            # cancel is a no-op on an already-finished task (Ray
            # semantics): only seal TaskCancelledError when the executor
            # reports the task errored or never produced results
            cancelled = (task_id in self._cancelled
                         and (err_name is not None or not results))
            if (spec.actor_id is not None and err_name in _ACTOR_SYS_ERRS
                    and not cancelled):
                # actor transport failure: the ordered submitter decides
                # (re-resolve + resubmit vs ActorDiedError) — outside the
                # lock; the spec stays pending meanwhile
                actor_handoff = spec
            elif err_name is not None and not cancelled and self._retriable(
                    spec, err_name):
                spec.attempt += 1
                resubmit = spec
            else:
                self._pending.pop(task_id, None)
                self._cancelled.discard(task_id)
                settled_spec = spec
                if cancelled:
                    err = TaskCancelledError(
                        f"task {task_id.hex()} cancelled")
                    payload = serialization.serialize(err).to_bytes()
                    for oid in spec.return_ids():
                        self._results[oid] = (payload, True)
                        sealed_oids.append(oid)
                elif err_name in _SYSTEM_ERRS and not results:
                    err = WorkerCrashedError(
                        f"direct task {spec.function_name} lost its "
                        f"executor ({err_name}), retries exhausted")
                    payload = serialization.serialize(err).to_bytes()
                    for oid in spec.return_ids():
                        self._results[oid] = (payload, True)
                        sealed_oids.append(oid)
                else:
                    store_resident: List[ObjectID] = []
                    for oid, payload, is_err in results:
                        if oid in self._dropped:
                            self._dropped.discard(oid)
                            # still sealed in the executor node's store:
                            # dependents resolve via the directory
                            sealed_oids.append(oid)
                        else:
                            self._results[oid] = (payload, is_err)
                            if payload is None and exec_hex:
                                self._result_nodes[oid] = exec_hex
                            if payload is None and not is_err:
                                store_resident.append(oid)
                            sealed_oids.append(oid)
                    if (store_resident and err_name is None
                            and spec.actor_id is None
                            and not spec.streaming):
                        # plain task with live store-sealed results:
                        # retain the spec for lineage reconstruction
                        # (actor results are not reconstructable; stream
                        # items have replay semantics of their own)
                        self._record_lineage_locked(spec, store_resident)
                if spec.streaming:
                    self._settle_stream_locked(
                        spec, err_name is not None or cancelled
                        or any(e for _o, _p, e in results))
                self._cv.notify_all()
        if settled_spec is not None or sealed_oids:
            self._wake_waiters()
        if actor_handoff is not None:
            # the ordered submitter either parks the call for resubmission
            # or seals an ATTRIBUTED ActorDiedError itself (it can resolve
            # the actor's death cause / restarting state; this manager
            # can't) — True means it took ownership either way
            handled = (self._actor_failed_cb is not None
                       and self._actor_failed_cb(actor_handoff, err_name))
            if not handled:
                from .exceptions import ActorDiedError

                self.seal_error_local(actor_handoff, ActorDiedError(
                    actor_handoff.actor_id,
                    f"actor call failed ({err_name}), not retried"))
            return
        if settled_spec is not None:
            self._release_pins(settled_spec)
            if (settled_spec.actor_id is not None
                    and self._actor_done_cb is not None):
                self._actor_done_cb(settled_spec)
        if sealed_oids:
            # downstream deferred tasks waiting on these results
            self.deps_available(sealed_oids)
        if resubmit is not None:
            resubmit.direct_hops = 0  # fresh routing for the retry
            self._submit(resubmit)

    # ------------------------------------------------------------ lineage

    def _record_lineage_locked(self, spec: TaskSpec,
                               store_oids: List[ObjectID]) -> None:
        from .config import global_config

        cap = global_config().direct_lineage_max
        if cap <= 0:
            return
        self._lineage[spec.task_id] = spec
        self._lineage_live[spec.task_id] = set(store_oids)
        while len(self._lineage) > cap:
            old_tid, _ = self._lineage.popitem(last=False)
            self._lineage_live.pop(old_tid, None)

    def owns_lineage(self, oid: ObjectID) -> bool:
        """True when ``oid``'s creating task can be resubmitted from this
        owner's lineage (or is already being re-executed)."""
        with self._lock:
            tid = oid.task_id()
            return tid in self._lineage or tid in self._pending

    def recover(self, oid: ObjectID) -> bool:
        """Lineage reconstruction: the store-sealed result ``oid`` has no
        live location, so resubmit its creating task (reference:
        object_recovery_manager.h:90 ``RecoverObject`` — resubmission
        respects ``max_retries``, and lost owned args recover
        recursively). Safe to call spuriously: re-execution reseals the
        same oids and getters simply read the fresh copy. Returns True
        when a recovery is running (now or already)."""
        probe_args: List[ObjectID] = []
        with self._lock:
            tid = oid.task_id()
            if tid in self._pending:
                return True  # already being re-executed
            spec = self._lineage.get(tid)
            if spec is None or spec.attempt >= spec.max_retries:
                return False
            # candidate owned args whose bytes were store-resident: their
            # nodes may be gone too — probed outside the lock (the locate
            # callback takes cluster locks)
            for aoid in spec.arg_object_ids():
                res = self._results.get(aoid)
                if res is not None and res[0] is None:
                    probe_args.append(aoid)
        lost_args: List[ObjectID] = []
        for aoid in probe_args:
            alive = None
            try:
                if self._locate is not None:
                    alive = self._locate(aoid)
                elif self._ext_wait is not None:
                    alive = bool(self._ext_wait([aoid], 0.0))
            except Exception:
                alive = None
            if not alive:
                lost_args.append(aoid)
        with self._lock:
            spec = self._lineage.pop(tid, None)
            if spec is None:
                return tid in self._pending
            self._lineage_live.pop(tid, None)
            spec.attempt += 1
            for roid in spec.return_ids():
                self._results.pop(roid, None)
                self._result_nodes.pop(roid, None)
            recover_first = []
            for aoid in lost_args:
                if aoid.task_id() in self._lineage:
                    # clear the stale entry so register() defers this
                    # spec on the arg until its producer reseals it
                    self._results.pop(aoid, None)
                    self._result_nodes.pop(aoid, None)
                    recover_first.append(aoid)
        for aoid in recover_first:
            self.recover(aoid)
        spec.direct_hops = 0
        spec.arg_hints = None  # stale node hints died with the node
        ready = self.register(spec)
        if ready is not None:
            self._submit(ready)
        return True

    def seal_error_local(self, spec: TaskSpec, exc: Exception) -> None:
        """Settle an owned task with ``exc`` on all its returns."""
        payload = serialization.serialize(exc).to_bytes()
        with self._lock:
            if self._pending.pop(spec.task_id, None) is None:
                return
            self._cancelled.discard(spec.task_id)
            self._deferred.pop(spec.task_id, None)
            for oid in spec.return_ids():
                self._results[oid] = (payload, True)
            if spec.streaming:
                self._settle_stream_locked(spec, True)
            self._cv.notify_all()
        self._wake_waiters()
        self._release_pins(spec)
        self.deps_available(spec.return_ids())

    # ------------------------------------------------------------ streams

    def _settle_stream_locked(self, spec: TaskSpec, is_err: bool) -> None:
        """Record stream EOF. Published streams keep their state and
        retained payloads for remote subscribers (bounded retention —
        see _retire_published_locked)."""
        tid = spec.task_id
        st = self._streams.get(tid)
        if st is None:
            st = self._streams[tid] = _StreamState()
        st.done = (st.count, is_err)
        if st.dropped:
            if st.published:
                self._retire_published_locked(tid)
            else:
                self._purge_stream_locked(tid, st)

    def _retire_published_locked(self, tid: TaskID) -> None:
        """A published stream is done and its local handle is gone: move
        it to the bounded retention FIFO; evict the oldest past the cap
        so a stream-heavy owner's memory stays bounded."""
        from .config import global_config

        cap = max(1, global_config().published_stream_retain_max)
        self._published_done[tid] = True
        self._published_done.move_to_end(tid)
        while len(self._published_done) > cap:
            old_tid, _ = self._published_done.popitem(last=False)
            st = self._streams.get(old_tid)
            if st is not None:
                st.handed.clear()  # retention over: free everything
                self._purge_stream_locked(old_tid, st)
                # the primary return's retained payload goes too
                prim = ObjectID.for_task_return(old_tid, 0)
                self._results.pop(prim, None)
                self._result_nodes.pop(prim, None)

    def _purge_stream_locked(self, tid: TaskID, st: _StreamState) -> None:
        """Free retained item payloads the consumer never read; items that
        were handed out as ObjectRefs release via their own ref drops."""
        for i in range(st.count):
            soid = ObjectID.for_stream(tid, i)
            if soid not in st.handed:
                self._results.pop(soid, None)
                self._result_nodes.pop(soid, None)
        self._streams.pop(tid, None)
        self._published_done.pop(tid, None)

    def publish_stream(self, task_id: TaskID) -> bool:
        """A generator handle for ``task_id`` is leaving this process
        (serialization): mark the stream published so its state (item
        table + EOF) is retained for remote subscribers, which read it
        straight from this owner over the ``stream_sub`` reply chain —
        nothing is mirrored to the head. Returns False when this manager
        does not own the stream (borrowed handle re-serialized — the
        subscriber keeps the original owner route)."""
        with self._lock:
            st = self._streams.get(task_id)
            spec = self._pending.get(task_id)
            if st is None and (spec is None or not spec.streaming):
                return False
            if st is None:
                st = self._streams[task_id] = _StreamState()
            st.published = True
            return True

    def on_stream_item(self, task_id: TaskID, index: int,
                       payload: Optional[bytes],
                       exec_hex: Optional[str] = None) -> None:
        """A streamed item announcement arriving over the direct reply
        chain (executor -> owner, FIFO with the final completion). Small
        items carry their payload inline; large ones are store-resident at
        ``exec_hex``. Items land in ``_results`` under their for_stream
        oid, so reads, hints for dependent tasks, and ref drops all reuse
        the normal owned-result machinery."""
        oid = ObjectID.for_stream(task_id, index)
        with self._lock:
            spec = self._pending.get(task_id)
            st = self._streams.get(task_id)
            if spec is None and st is None:
                return  # settled and consumed (or never ours): stale
            if st is None:
                st = self._streams[task_id] = _StreamState()
            if st.dropped and not st.published:
                return  # generator released, nobody else has it: discard
            if index + 1 > st.count:
                st.count = index + 1  # EOF total counts published items too
            if exec_hex:
                st.exec_hex = exec_hex
            # retained even when the LOCAL handle is gone, as long as the
            # stream is published: remote subscribers read items from here
            self._results[oid] = (payload, False)
            if payload is None and exec_hex:
                self._result_nodes[oid] = exec_hex
            self._cv.notify_all()
        self._wake_waiters()
        # downstream tasks may be deferred on this item ref
        self.deps_available([oid])

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: Optional[float]):
        """Owner-side next-item protocol (same contract as the head's
        stream_next): ("item", oid) | ("end", total) | ("error",) |
        ("wait",) after ``timeout``. Returns None when this manager does
        not own the stream (caller falls back to the head path)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while True:
                st = self._streams.get(task_id)
                if st is not None and index < st.count:
                    oid = ObjectID.for_stream(task_id, index)
                    st.handed.add(oid)
                    return ("item", oid)
                pending = task_id in self._pending
                if not pending:
                    if st is None or st.done is None:
                        return None  # not direct-owned: head path
                    total, is_err = st.done
                    return ("error",) if is_err else ("end", total)
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    return ("wait",)
                self._cv.wait(remaining if remaining is not None else 0.2)

    def stream_next_remote(self, task_id: TaskID, index: int,
                           timeout: Optional[float]):
        """Serve one bounded ``stream_sub`` round for a REMOTE subscriber
        (a consumer in another process reading a published stream straight
        from this owner). Replies:

          ("item", oid, payload | None, hint | None)  — inline payloads
              ship in the reply; store-resident items carry the executor
              node hex, and the subscriber pulls the bytes peer-to-peer.
          ("end", total) | ("wait",)
          ("error", primary_payload | None) — the primary return's error
              bytes ride along so owner-sealed failures (never executed)
              are resolvable without a store location.
          None — this manager does not own the stream (the caller reports
              the owner gone)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while True:
                st = self._streams.get(task_id)
                if st is not None and index < st.count:
                    oid = ObjectID.for_stream(task_id, index)
                    res = self._results.get(oid)
                    payload = res[0] if res else None
                    # hint always rides along: inline items ALSO have a
                    # store copy at the executor node (sealed before the
                    # announcement), the consumer's fallback when its own
                    # store can't hold the shipped payload
                    hint = self._result_nodes.get(oid) or st.exec_hex
                    return ("item", oid, payload, hint)
                pending = task_id in self._pending
                if not pending:
                    if st is None or st.done is None:
                        return None  # not owned here: owner route is stale
                    total, is_err = st.done
                    if is_err:
                        prim = self._results.get(
                            ObjectID.for_task_return(task_id, 0))
                        return ("error", prim[0] if prim else None)
                    return ("end", total)
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    return ("wait",)
                self._cv.wait(remaining if remaining is not None else 0.2)

    def stamp_hints(self, spec: TaskSpec) -> None:
        with self._lock:
            self._stamp_hints_locked(spec)

    @staticmethod
    def _retriable(spec: TaskSpec, err_name: str) -> bool:
        if spec.attempt >= spec.max_retries:
            return False
        if err_name in _SYSTEM_ERRS:
            return True
        return spec.retry_exceptions

    # ------------------------------------------------------------ reads

    def owns(self, oid: ObjectID) -> bool:
        with self._lock:
            return (oid in self._results
                    or oid.task_id() in self._pending)

    def get_local(self, oid: ObjectID,
                  timeout: Optional[float]) -> Optional[Tuple[Optional[bytes], bool]]:
        """Blocking read of an owned result. Returns (payload|None, is_err),
        or None if this manager does not own the object. A None payload
        means the bytes live in a node store — caller falls through to the
        store path."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while True:
                if oid in self._results:
                    return self._results[oid]
                if oid.task_id() not in self._pending:
                    return None
                # one shared deadline across wakeups: every completion
                # notifies this cv, so a per-wait timeout would restart
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    from .exceptions import GetTimeoutError

                    raise GetTimeoutError(f"get timed out on {oid.hex()}")
                self._cv.wait(remaining)

    def result_node(self, oid: ObjectID) -> Optional[str]:
        """Node hex that sealed a store-resident owned result, if known."""
        with self._lock:
            return self._result_nodes.get(oid)

    def fill_result_locations(self, oids, locations) -> None:
        """Backfill empty slots of a head-directory ``object_locations``
        answer from the owner's direct result table (direct-owned results
        the head hasn't learned about yet). Mutates ``locations`` in
        place; the one ownership rule both driver and worker lookups
        share."""
        for i, oid in enumerate(oids):
            if not locations[i]:
                h = self.result_node(oid)
                if h:
                    locations[i] = [h]

    def ready_subset(self, oids) -> set:
        """Non-blocking: which of ``oids`` are completed owned results."""
        with self._lock:
            return {o for o in oids if o in self._results}

    def pending_oids(self, oids) -> set:
        """Which of ``oids`` belong to still-pending owned tasks."""
        with self._lock:
            return {o for o in oids if o.task_id() in self._pending}

    def drop(self, oid: ObjectID) -> None:
        """Owner released its ref: free the retained inline result (or
        mark a still-pending task's result discard-on-arrival). Dropping
        a stream's primary return (the generator handle died) purges the
        stream's unread items."""
        with self._lock:
            self._result_nodes.pop(oid, None)
            if self._results.pop(oid, None) is None \
                    and oid.task_id() in self._pending:
                self._dropped.add(oid)
            tid = oid.task_id()
            live = self._lineage_live.get(tid)
            if live is not None:
                live.discard(oid)
                if not live:
                    self._lineage_live.pop(tid, None)
                    self._lineage.pop(tid, None)
            st = self._streams.get(tid)
            if st is not None:
                st.handed.discard(oid)
                if oid == ObjectID.for_task_return(tid, 0):
                    st.dropped = True
                    # published streams keep their state (serialized
                    # handles elsewhere still subscribe here) under the
                    # bounded retention FIFO; unpublished ones purge now
                    if tid not in self._pending:
                        if st.published:
                            if st.done is not None:
                                self._retire_published_locked(tid)
                        else:
                            self._purge_stream_locked(tid, st)


class _ActorRoute:
    """Per-(owner, actor) submission state."""

    __slots__ = ("seq", "loc", "state", "queue", "ready", "inflight",
                 "parked", "death_cause", "send_buf", "sender_active",
                 "loc_bounces", "last_bounce_loc")

    def __init__(self):
        self.seq = 0
        self.loc: Optional[str] = None
        # UNRESOLVED | READY | WAITING | DEAD
        self.state = "UNRESOLVED"
        self.queue: List[TaskSpec] = []     # submitted, unsent (seq order)
        self.ready: set = set()             # task_ids with deps resolved
        self.inflight: Dict[TaskID, TaskSpec] = {}
        self.parked: List[TaskSpec] = []    # failed in flight, to resubmit
        self.death_cause: Optional[str] = None
        # sends must leave the lock (reentrancy) yet stay ordered: the
        # ready prefix moves into send_buf and exactly ONE thread drains it
        self.send_buf: List[TaskSpec] = []
        self.sender_active = False
        # consecutive ActorMissingError bounces against the same resolved
        # location: the FSM lags the node's worker table by a beat, and
        # resubmitting into the stale answer instantly would spin the
        # submit->bounce->resolve cycle hot (GIL-starving the very reader
        # thread that would update the FSM). Past a few bounces the
        # resolver holds the route until its backoff tick instead.
        self.loc_bounces = 0
        self.last_bounce_loc: Optional[str] = None


class DirectActorSubmitter:
    """Owner-side ordered actor-call submission, head out of the path.

    The analog of the reference's ActorTaskSubmitter + sequential submit
    queue (``src/ray/core_worker/transport/actor_task_submitter.cc:482``
    ``PushActorTask``, ``sequential_actor_submit_queue.cc``): calls carry a
    per-(owner, actor) sequence number, ride the owner's node channel to
    the actor's node (FIFO per route preserves order), and the executor
    replies straight to the owner. The head is consulted only to RESOLVE
    the actor's location (once per incarnation) and keeps the lifecycle
    FSM; it never sees individual method calls.

    Failure protocol: a location error (ActorMissingError/NodeDiedError —
    the call never ran) parks the call for resubmission after the resolver
    re-learns the actor's address; a death mid-call (ActorDiedError/
    WorkerCrashedError) parks only when ``max_task_retries`` allows,
    otherwise seals ActorDiedError (reference at-most-once semantics).
    Parked + queued calls flush to the restarted actor in seq order.
    """

    def __init__(self, manager: DirectTaskManager,
                 send: Callable[[TaskSpec], None],
                 resolve: Callable[[Any], Optional[dict]]):
        self._mgr = manager
        self._send = send
        self._resolve = resolve
        self._lock = threading.Lock()
        self._routes: Dict[Any, _ActorRoute] = {}
        self._resolve_kick = threading.Event()
        self._resolve_queue: set = set()  # actor_ids needing resolution
        self._resolver_started = False
        self._drained_cv = threading.Condition(self._lock)
        manager._actor_ready_cb = self._on_dep_ready
        manager._actor_failed_cb = self._on_call_failed
        manager._actor_done_cb = self.on_call_done
        manager._actor_cancel_cb = self.remove_call

    # ------------------------------------------------------------ submit

    def try_submit(self, spec: TaskSpec) -> bool:
        """Returns True if the call was taken onto the direct path; False
        = caller must use the head path (ineligible)."""
        if not actor_call_eligible(spec):
            return False
        aid = spec.actor_id
        with self._lock:
            rt = self._routes.setdefault(aid, _ActorRoute())
            spec.actor_seq = rt.seq
            rt.seq += 1
            # append under the SAME lock as seq assignment: the queue's
            # seq-sorted invariant is what the prefix drain relies on
            rt.queue.append(spec)
        ready = self._mgr.register(spec)
        dead_cause = None
        with self._lock:
            rt = self._routes[aid]
            if rt.state == "DEAD":
                dead_cause = rt.death_cause or "actor is dead"
                try:
                    rt.queue.remove(spec)
                except ValueError:
                    pass
            elif ready is not None:
                rt.ready.add(spec.task_id)
        if dead_cause is not None:
            from .exceptions import ActorDiedError

            self._mgr.seal_error_local(spec, ActorDiedError(aid, dead_cause))
            return True
        self._drain(aid)
        return True

    # ------------------------------------------------------------ drain

    def _drain(self, aid) -> None:
        """Send the longest dep-ready prefix of the queue (order gate:
        a call with unresolved deps blocks everything behind it, matching
        the reference's in-order actor scheduling queue). Sends happen
        outside the lock but single-threaded per route (sender_active)."""
        kick = False
        i_am_sender = False
        with self._lock:
            rt = self._routes.get(aid)
            if rt is None:
                return
            if rt.state in ("UNRESOLVED", "WAITING"):
                rt.state = "WAITING"
                self._resolve_queue.add(aid)
                kick = True
            elif rt.state == "READY":
                while rt.queue and rt.queue[0].task_id in rt.ready:
                    spec = rt.queue.pop(0)
                    rt.ready.discard(spec.task_id)
                    spec.actor_node_hex = rt.loc
                    rt.inflight[spec.task_id] = spec
                    rt.send_buf.append(spec)
                if rt.send_buf and not rt.sender_active:
                    rt.sender_active = True
                    i_am_sender = True
        if kick:
            self._ensure_resolver()
            self._resolve_kick.set()
        if not i_am_sender:
            return
        while True:
            with self._lock:
                rt = self._routes.get(aid)
                if rt is None or not rt.send_buf:
                    if rt is not None:
                        rt.sender_active = False
                    return
                spec = rt.send_buf.pop(0)
            # fresh routing decision per send: a prior forward stamped
            # direct_hops on this (shared) spec; without the reset a
            # parked-and-resubmitted call would bounce ActorMissingError
            # forever at the routing node
            spec.direct_hops = 0
            self._mgr.stamp_hints(spec)
            self._send(spec)

    def _on_dep_ready(self, spec: TaskSpec) -> None:
        aid = spec.actor_id
        with self._lock:
            rt = self._routes.get(aid)
            if rt is None:
                return
            rt.ready.add(spec.task_id)
        self._drain(aid)

    # ------------------------------------------------------------ failure

    def _on_call_failed(self, spec: TaskSpec, err_name: str) -> bool:
        """Transport/executor failure for an in-flight call. True = this
        submitter took ownership: parked for resubmission, or sealed an
        attributed ActorDiedError (death cause + restarting state from
        the actor FSM). False = let the manager seal a generic error."""
        aid = spec.actor_id
        retry_ok = (err_name in _ACTOR_LOC_ERRS
                    or spec.attempt < spec.max_retries)
        with self._lock:
            rt = self._routes.get(aid)
            if rt is None or rt.state == "DEAD" or not retry_ok:
                if rt is not None:
                    rt.inflight.pop(spec.task_id, None)
                    self._drained_cv.notify_all()
                if rt is not None and rt.state == "DEAD":
                    dead_cause = rt.death_cause or "actor is dead"
                else:
                    dead_cause = None
            else:
                dead_cause = ()  # sentinel: retry path below
        if dead_cause is None or isinstance(dead_cause, str):
            # retries exhausted (or route gone): seal with the actor
            # FSM's attributed cause; flag restarting when the actor
            # itself is coming back but THIS call's budget is spent
            from .exceptions import ActorDiedError

            cause, restarting = dead_cause, False
            if cause is None:
                # the failure reply and the crash report race out of the
                # actor's node: give the FSM a bounded moment to learn
                # the attributed cause before sealing. Kept SHORT — this
                # runs on the owner's reply-processing chain, so every
                # reply behind it waits; the node reports the crash to
                # the head BEFORE replying (node.py _on_worker_dead), so
                # the first resolve normally already has the cause.
                import time as _time

                deadline = _time.monotonic() + 0.5
                while True:
                    try:
                        info = self._resolve(aid)
                    except Exception:
                        info = None
                    if info is not None:
                        cause = info.get("death_cause")
                        restarting = info.get("state") in (
                            "RESTARTING", "PENDING_CREATION")
                    if (info is None or cause
                            or info.get("state") == "DEAD"
                            or _time.monotonic() >= deadline):
                        break
                    _time.sleep(0.05)
            self._mgr.seal_error_local(spec, ActorDiedError(
                aid, cause or f"actor call failed ({err_name}), "
                              "retries exhausted",
                restarting=restarting))
            return True
        with self._lock:
            rt = self._routes.get(aid)
            if rt is None:
                return False
            rt.inflight.pop(spec.task_id, None)
            if rt.state == "DEAD":
                # the route died between the two lock windows: parking
                # now would strand the call forever (_actor_dead already
                # flushed parked+queued)
                died_between = rt.death_cause or "actor is dead"
            else:
                died_between = None
                if err_name not in _ACTOR_LOC_ERRS:
                    spec.attempt += 1  # executed-and-died consumes a retry
                    rt.loc_bounces = 0
                else:
                    rt.loc_bounces += 1
                    rt.last_bounce_loc = spec.actor_node_hex or rt.loc
                rt.parked.append(spec)
                rt.state = "WAITING"
                rt.loc = None
                self._resolve_queue.add(aid)
        if died_between is not None:
            from .exceptions import ActorDiedError

            self._mgr.seal_error_local(
                spec, ActorDiedError(aid, died_between))
            return True
        self._ensure_resolver()
        self._resolve_kick.set()
        return True

    # ------------------------------------------------------------ resolver

    def _ensure_resolver(self) -> None:
        with self._lock:
            if self._resolver_started:
                return
            self._resolver_started = True
        threading.Thread(target=self._resolve_loop, daemon=True,
                         name="actor-resolver").start()

    def _resolve_loop(self) -> None:
        """Location resolution + restart watching (reference: actor table
        subscription in GcsClient; here a poll while calls are parked)."""
        backoff = 0.02
        while True:
            self._resolve_kick.wait(timeout=0.5)
            self._resolve_kick.clear()
            with self._lock:
                pending = list(self._resolve_queue)
            if not pending:
                backoff = 0.02
                continue
            progress = False
            for aid in pending:
                try:
                    info = self._resolve(aid)
                except Exception:
                    continue  # control link hiccup; retry next round
                if info is not None and info.get("state") == "ALIVE" \
                        and info.get("node_hex"):
                    with self._lock:
                        rt = self._routes.get(aid)
                        stale = (rt is not None and rt.loc_bounces >= 3
                                 and info["node_hex"] == rt.last_bounce_loc)
                        if stale:
                            # the same answer keeps bouncing: hold the
                            # route THIS round and let the backoff tick
                            # retry — the FSM (or a bounced head's node
                            # table) is lagging. The streak DECAYS per
                            # held round, so the route always resubmits
                            # again at backoff cadence instead of either
                            # hot-spinning or parking forever.
                            rt.loc_bounces -= 1
                    if stale:
                        continue
                    self._actor_alive(aid, info["node_hex"])
                    progress = True
                elif info is None or info.get("state") == "DEAD":
                    self._actor_dead(aid, (info or {}).get(
                        "death_cause") or "actor is dead")
                    progress = True
                # PENDING_CREATION / RESTARTING: keep polling
            if not progress:
                self._resolve_kick.wait(timeout=backoff)
                self._resolve_kick.clear()
                backoff = min(backoff * 2, 0.5)
                with self._lock:
                    if self._resolve_queue:
                        self._resolve_kick.set()
            else:
                backoff = 0.02

    def _actor_alive(self, aid, node_hex: str) -> None:
        with self._lock:
            rt = self._routes.get(aid)
            if rt is None:
                self._resolve_queue.discard(aid)
                return
            rt.loc = node_hex
            if rt.state == "WAITING":
                rt.state = "READY"
            self._resolve_queue.discard(aid)
            if rt.parked:
                # failed calls precede queued-unsent ones (lower seq);
                # they re-enter the queue front in seq order
                rt.parked.sort(key=lambda s: s.actor_seq)
                for spec in reversed(rt.parked):
                    rt.queue.insert(0, spec)
                    rt.ready.add(spec.task_id)
                rt.parked.clear()
        self._drain(aid)

    def _actor_dead(self, aid, cause: str) -> None:
        from .exceptions import ActorDiedError

        with self._lock:
            rt = self._routes.get(aid)
            self._resolve_queue.discard(aid)
            if rt is None:
                return
            rt.state = "DEAD"
            rt.death_cause = cause
            rt.loc = None
            to_fail = rt.parked + rt.queue
            rt.parked = []
            rt.queue = []
            rt.ready.clear()
            self._drained_cv.notify_all()
        for spec in to_fail:
            self._mgr.seal_error_local(
                spec, ActorDiedError(aid, cause))

    # ------------------------------------------------------------ complete

    def on_call_done(self, spec: TaskSpec) -> None:
        """Successful completion bookkeeping (called by the runtime after
        manager.complete seals results)."""
        with self._lock:
            rt = self._routes.get(spec.actor_id)
            if rt is not None:
                rt.inflight.pop(spec.task_id, None)
                rt.loc_bounces = 0  # the route works: reset the streak
                self._drained_cv.notify_all()

    def remove_call(self, spec: TaskSpec) -> None:
        """A call settled outside the normal flow (owner-side cancel):
        remove it from every route structure so the ordered queue drains
        past it."""
        aid = spec.actor_id
        with self._lock:
            rt = self._routes.get(aid)
            if rt is None:
                return
            rt.ready.discard(spec.task_id)
            for lst in (rt.queue, rt.parked, rt.send_buf):
                for i, s in enumerate(lst):
                    if s.task_id == spec.task_id:
                        del lst[i]
                        break
            rt.inflight.pop(spec.task_id, None)
            self._drained_cv.notify_all()
        self._drain(aid)
