"""Direct (head-bypass) task path: owner-side task table + eligibility.

The reference keeps the GCS out of the normal-task hot path entirely: the
submitting CoreWorker owns the task (retries, result table), resolves its
dependencies locally (``src/ray/core_worker/transport/dependency_resolver.h:29``
``LocalDependencyResolver``), leases a worker from its *local* raylet, and
pushes the task directly
(``src/ray/core_worker/transport/normal_task_submitter.cc:355``,
``reference_count.h:61`` — ownership lives with the submitter). Round 2 of
this framework routed every submit/finish through the single Head, capping
throughput at what one GIL-bound process can relay.

This module is the submitter side of the same decentralization: eligible
plain tasks go straight to the submitting process's *node* (worker → its
node over the existing channel; driver → the in-process head node), which
executes them from its own worker pool — or spills them one hop to a peer
node over the daemon↔daemon mesh — and replies directly to the owner.
The head only sees small *batched* event reports (object locations +
observability), amortized hundreds of tasks per message.

Ref args are resolved **owner-side** before submission (the analog of
``LocalDependencyResolver``): args produced by this owner's own direct
tasks resolve in-process (inline payloads ship as hints in the spec; large
results ship the sealing node's hex so the executor pulls peer-to-peer);
external objects are waited on via the object directory, then submitted.
A task never occupies a worker slot while its dependencies are pending.

Ownership semantics match the reference: if the owner dies, its in-flight
direct tasks and their results are lost (Ray's owner-died behavior); if
the executor dies, the owner retries per ``max_retries``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from . import serialization
from .exceptions import TaskCancelledError, WorkerCrashedError
from .ids import ObjectID, TaskID
from .task_spec import TaskSpec

# resources a node can grant from its worker-pool slots without head-side
# accounting (unit-instance resources like TPU need index binding; custom
# resources need cluster placement)
_DIRECT_RESOURCES = {"CPU"}

_SYSTEM_ERRS = ("WorkerCrashedError", "NodeDiedError")

# inline-hint ceiling: small owned results are copied into the spec so the
# executor never touches a store for them (mirrors the inline-arg path)
_INLINE_HINT_MAX = 100 * 1024


def direct_eligible(spec: TaskSpec) -> bool:
    """Hot-class test: plain <=1-CPU task, default placement. Ref args are
    fine — the owner resolves them before submission (dependency resolver)
    and the executor pulls via location hints. num_cpus>1 needs real
    resource accounting (a node grants direct tasks one worker SLOT, ~1
    CPU), so it keeps the head path."""
    s = spec.scheduling_strategy
    return (
        spec.actor_id is None
        and not spec.is_actor_creation
        and not spec.streaming
        and spec.runtime_env is None
        and s.kind == "DEFAULT"
        and s.placement_group_id is None
        and s.node_id is None
        and all(k in _DIRECT_RESOURCES for k, _ in spec.resources)
        and spec.resources.get("CPU") <= 1.0
    )


class DirectTaskManager:
    """Owner-side table of in-flight direct tasks + their inline results.

    The analog of the reference CoreWorker's ``TaskManager`` + in-process
    memory store + ``LocalDependencyResolver`` (``task_manager.h:208``,
    ``memory_store.cc``, ``dependency_resolver.h:29``): completion wakes
    local getters; system failures retry by resubmitting through the
    ``submit`` callback; user errors deserialize to raised exceptions;
    ref-arg tasks defer until every dependency is available somewhere.

    Optional collaborators (wired by the owning runtime):
      - ``ext_wait(oids, timeout) -> ready_list``: one bounded round of
        availability-checking external (non-owned) objects against the
        cluster object directory.
      - ``pin(oids)`` / ``unpin(oids)``: keep ``spec.pinned_args`` alive
        while the task is in flight (reference: submitter arg pinning).
    """

    def __init__(self, submit: Callable[[TaskSpec], None],
                 ext_wait: Optional[Callable] = None,
                 pin: Optional[Callable] = None,
                 unpin: Optional[Callable] = None):
        self._submit = submit
        self._ext_wait = ext_wait
        self._pin = pin
        self._unpin = unpin
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: Dict[TaskID, TaskSpec] = {}
        self._cancelled: set = set()
        # oids whose ObjectRef died before the task completed: their
        # results are discarded on arrival instead of retained forever
        self._dropped: set = set()
        # oid -> (payload bytes | None, is_error); None payload = large
        # result sealed in the executor node's store (get falls back to the
        # store/locate path)
        self._results: Dict[ObjectID, Tuple[Optional[bytes], bool]] = {}
        # oid -> node hex that sealed a large (store-resident) result;
        # shipped as a pull hint when the oid is a downstream task's arg
        self._result_nodes: Dict[ObjectID, str] = {}
        # ---- dependency resolver state ---------------------------------
        # task_id -> set of oids still unavailable; submit fires when empty
        self._deferred: Dict[TaskID, Set[ObjectID]] = {}
        # external (non-owned) oid -> task_ids waiting on it
        self._ext_waiting: Dict[ObjectID, Set[TaskID]] = {}
        self._poller_started = False

    # ------------------------------------------------------------ submit

    def register(self, spec: TaskSpec) -> Optional[TaskSpec]:
        """Record ownership; resolve dependencies. Returns the spec when it
        is ready to submit now, or None if it was deferred (the resolver
        submits it when its deps become available)."""
        if self._pin is not None and spec.pinned_args:
            try:
                self._pin(list(spec.pinned_args))
            except Exception:
                pass
        arg_ids = spec.arg_object_ids()
        with self._lock:
            self._pending[spec.task_id] = spec
            if not arg_ids:
                return spec
            owned: List[ObjectID] = []
            ext: List[ObjectID] = []
            for oid in arg_ids:
                if oid in self._results:
                    continue  # owned + completed: hint stamped at submit
                if oid.task_id() in self._pending:
                    owned.append(oid)  # owned + still running
                else:
                    ext.append(oid)  # external: availability via directory
            if not owned and not ext:
                self._stamp_hints_locked(spec)
                return spec
        # synchronous availability probe for external deps (outside the
        # lock — the probe takes cluster locks / an RPC): the common case
        # (args already materialized) submits immediately
        if ext and self._ext_wait is not None:
            try:
                ready_now = set(self._ext_wait(list(ext), 0.0))
            except Exception:
                ready_now = set()
            ext = [o for o in ext if o not in ready_now]
        with self._lock:
            # re-check under the lock: owned deps may have completed (or
            # external ones sealed) during the probe window
            missing = {o for o in owned if o not in self._results}
            missing.update(o for o in ext if o not in self._results)
            if not missing:
                self._stamp_hints_locked(spec)
                return spec
            self._deferred[spec.task_id] = missing
            ext_missing = [o for o in ext if o in missing]
            for oid in ext_missing:
                self._ext_waiting.setdefault(oid, set()).add(spec.task_id)
            if ext_missing:
                self._ensure_poller_locked()
        return None

    def _stamp_hints_locked(self, spec: TaskSpec) -> None:
        """Attach resolution hints for args this owner knows about."""
        hints: Dict[ObjectID, tuple] = {}
        for oid in spec.arg_object_ids():
            res = self._results.get(oid)
            if res is not None:
                payload, is_err = res
                if payload is not None and len(payload) <= _INLINE_HINT_MAX:
                    hints[oid] = ("inline", payload, is_err)
                    continue
                node_hex = self._result_nodes.get(oid)
                if node_hex:
                    hints[oid] = ("node", node_hex)
        if hints:
            spec.arg_hints = hints

    def _ensure_poller_locked(self) -> None:
        if self._poller_started or self._ext_wait is None:
            return
        self._poller_started = True
        threading.Thread(target=self._poll_external, daemon=True,
                         name="direct-dep-poller").start()

    def _poll_external(self) -> None:
        """Availability loop for external deps: one bounded ``ext_wait``
        round over the union of outstanding oids (the directory wait is
        cv-based on the head, so readiness propagates promptly)."""
        while True:
            with self._lock:
                oids = list(self._ext_waiting.keys())
                if not oids:
                    self._poller_started = False
                    return
            try:
                ready = self._ext_wait(oids, 0.2)
            except Exception:
                ready = []
            if ready:
                self.deps_available(ready)

    def deps_available(self, oids) -> None:
        """Mark objects available; submit any deferred spec whose last
        missing dependency this satisfies."""
        to_submit: List[TaskSpec] = []
        ready_set = set(oids)
        with self._lock:
            for oid in ready_set:
                self._ext_waiting.pop(oid, None)
            for tid, deps in list(self._deferred.items()):
                deps -= ready_set
                if not deps:
                    del self._deferred[tid]
                    spec = self._pending.get(tid)
                    if spec is not None and tid not in self._cancelled:
                        self._stamp_hints_locked(spec)
                        to_submit.append(spec)
        for spec in to_submit:
            self._submit(spec)

    def cancel(self, oid: ObjectID) -> bool:
        """Owner-side cancel: mark so the (already-running) result seals
        TaskCancelledError on arrival; a still-deferred task is cancelled
        entirely owner-side. Returns True if it was pending."""
        sealed_spec = None
        with self._lock:
            tid = oid.task_id()
            if tid not in self._pending:
                return False
            self._cancelled.add(tid)
            if tid in self._deferred:
                # never submitted: settle in place
                del self._deferred[tid]
                for waiters in self._ext_waiting.values():
                    waiters.discard(tid)
                sealed_spec = self._pending.pop(tid)
                self._cancelled.discard(tid)
                err = TaskCancelledError(f"task {tid.hex()} cancelled")
                payload = serialization.serialize(err).to_bytes()
                for roid in sealed_spec.return_ids():
                    self._results[roid] = (payload, True)
                self._cv.notify_all()
        if sealed_spec is not None:
            self._release_pins(sealed_spec)
            # downstream tasks deferred on this task's returns must wake
            # (they will run and raise the sealed TaskCancelledError)
            self.deps_available(sealed_spec.return_ids())
        return True

    def _release_pins(self, spec: TaskSpec) -> None:
        if self._unpin is not None and spec.pinned_args:
            try:
                self._unpin(list(spec.pinned_args))
            except Exception:
                pass

    # ------------------------------------------------------------ complete

    def complete(self, task_id: TaskID, err_name: Optional[str],
                 results: List[Tuple[ObjectID, Optional[bytes], bool]],
                 exec_hex: Optional[str] = None) -> None:
        """Executor reply. ``results`` entries: (oid, inline payload | None
        for store-sealed, is_error); ``exec_hex`` = node that sealed
        store-resident results (pull hint for dependents)."""
        resubmit = None
        settled_spec = None
        sealed_oids: List[ObjectID] = []
        with self._lock:
            spec = self._pending.get(task_id)
            if spec is None:
                return  # stale (superseded attempt)
            # cancel is a no-op on an already-finished task (Ray
            # semantics): only seal TaskCancelledError when the executor
            # reports the task errored or never produced results
            cancelled = (task_id in self._cancelled
                         and (err_name is not None or not results))
            if err_name is not None and not cancelled and self._retriable(
                    spec, err_name):
                spec.attempt += 1
                resubmit = spec
            else:
                self._pending.pop(task_id, None)
                self._cancelled.discard(task_id)
                settled_spec = spec
                if cancelled:
                    err = TaskCancelledError(
                        f"task {task_id.hex()} cancelled")
                    payload = serialization.serialize(err).to_bytes()
                    for oid in spec.return_ids():
                        self._results[oid] = (payload, True)
                        sealed_oids.append(oid)
                elif err_name in _SYSTEM_ERRS and not results:
                    err = WorkerCrashedError(
                        f"direct task {spec.function_name} lost its "
                        f"executor ({err_name}), retries exhausted")
                    payload = serialization.serialize(err).to_bytes()
                    for oid in spec.return_ids():
                        self._results[oid] = (payload, True)
                        sealed_oids.append(oid)
                else:
                    for oid, payload, is_err in results:
                        if oid in self._dropped:
                            self._dropped.discard(oid)
                            # still sealed in the executor node's store:
                            # dependents resolve via the directory
                            sealed_oids.append(oid)
                        else:
                            self._results[oid] = (payload, is_err)
                            if payload is None and exec_hex:
                                self._result_nodes[oid] = exec_hex
                            sealed_oids.append(oid)
                self._cv.notify_all()
        if settled_spec is not None:
            self._release_pins(settled_spec)
        if sealed_oids:
            # downstream deferred tasks waiting on these results
            self.deps_available(sealed_oids)
        if resubmit is not None:
            self._submit(resubmit)

    @staticmethod
    def _retriable(spec: TaskSpec, err_name: str) -> bool:
        if spec.attempt >= spec.max_retries:
            return False
        if err_name in _SYSTEM_ERRS:
            return True
        return spec.retry_exceptions

    # ------------------------------------------------------------ reads

    def owns(self, oid: ObjectID) -> bool:
        with self._lock:
            return (oid in self._results
                    or oid.task_id() in self._pending)

    def get_local(self, oid: ObjectID,
                  timeout: Optional[float]) -> Optional[Tuple[Optional[bytes], bool]]:
        """Blocking read of an owned result. Returns (payload|None, is_err),
        or None if this manager does not own the object. A None payload
        means the bytes live in a node store — caller falls through to the
        store path."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while True:
                if oid in self._results:
                    return self._results[oid]
                if oid.task_id() not in self._pending:
                    return None
                # one shared deadline across wakeups: every completion
                # notifies this cv, so a per-wait timeout would restart
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    from .exceptions import GetTimeoutError

                    raise GetTimeoutError(f"get timed out on {oid.hex()}")
                self._cv.wait(remaining)

    def result_node(self, oid: ObjectID) -> Optional[str]:
        """Node hex that sealed a store-resident owned result, if known."""
        with self._lock:
            return self._result_nodes.get(oid)

    def ready_subset(self, oids) -> set:
        """Non-blocking: which of ``oids`` are completed owned results."""
        with self._lock:
            return {o for o in oids if o in self._results}

    def pending_oids(self, oids) -> set:
        """Which of ``oids`` belong to still-pending owned tasks."""
        with self._lock:
            return {o for o in oids if o.task_id() in self._pending}

    def wait_any(self, timeout: Optional[float]) -> None:
        """Block until any completion lands (wait() integration)."""
        with self._lock:
            self._cv.wait(timeout)

    def drop(self, oid: ObjectID) -> None:
        """Owner released its ref: free the retained inline result (or
        mark a still-pending task's result discard-on-arrival)."""
        with self._lock:
            self._result_nodes.pop(oid, None)
            if self._results.pop(oid, None) is None \
                    and oid.task_id() in self._pending:
                self._dropped.add(oid)
