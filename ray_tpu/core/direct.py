"""Direct (head-bypass) task path: owner-side task table + eligibility.

The reference keeps the GCS out of the normal-task hot path entirely: the
submitting CoreWorker owns the task (retries, result table), leases a
worker from its *local* raylet, and pushes the task directly
(``src/ray/core_worker/transport/normal_task_submitter.cc:355``,
``reference_count.h:61`` — ownership lives with the submitter). Round 2 of
this framework routed every submit/finish through the single Head, capping
throughput at what one GIL-bound process can relay.

This module is the submitter side of the same decentralization: eligible
plain tasks go straight to the submitting process's *node* (worker → its
node over the existing channel; driver → the in-process head node), which
executes them from its own worker pool — or spills them one hop to a peer
node over the daemon↔daemon mesh — and replies directly to the owner.
The head only sees small *batched* event reports (object locations +
observability), amortized hundreds of tasks per message.

Ownership semantics match the reference: if the owner dies, its in-flight
direct tasks and their results are lost (Ray's owner-died behavior); if
the executor dies, the owner retries per ``max_retries``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import serialization
from .exceptions import TaskCancelledError, WorkerCrashedError
from .ids import ObjectID, TaskID
from .task_spec import TaskSpec

# resources a node can grant from its worker-pool slots without head-side
# accounting (unit-instance resources like TPU need index binding; custom
# resources need cluster placement)
_DIRECT_RESOURCES = {"CPU"}

_SYSTEM_ERRS = ("WorkerCrashedError", "NodeDiedError")


def direct_eligible(spec: TaskSpec) -> bool:
    """Conservative hot-class test: plain <=1-CPU task, default placement,
    inline args only. Ref args would need dependency staging at the node;
    num_cpus>1 needs real resource accounting (a node grants direct tasks
    one worker SLOT, ~1 CPU); both keep the head path."""
    s = spec.scheduling_strategy
    return (
        spec.actor_id is None
        and not spec.is_actor_creation
        and not spec.streaming
        and spec.runtime_env is None
        and s.kind == "DEFAULT"
        and s.placement_group_id is None
        and s.node_id is None
        and not spec.arg_object_ids()
        and all(k in _DIRECT_RESOURCES for k, _ in spec.resources)
        and spec.resources.get("CPU") <= 1.0
    )


class DirectTaskManager:
    """Owner-side table of in-flight direct tasks + their inline results.

    The analog of the reference CoreWorker's ``TaskManager`` + in-process
    memory store (``task_manager.h:208``, ``memory_store.cc``): completion
    wakes local getters; system failures retry by resubmitting through the
    ``submit`` callback; user errors deserialize to raised exceptions.
    """

    def __init__(self, submit: Callable[[TaskSpec], None]):
        self._submit = submit
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: Dict[TaskID, TaskSpec] = {}
        self._cancelled: set = set()
        # oids whose ObjectRef died before the task completed: their
        # results are discarded on arrival instead of retained forever
        self._dropped: set = set()
        # oid -> (payload bytes | None, is_error); None payload = large
        # result sealed in the executor node's store (get falls back to the
        # store/locate path)
        self._results: Dict[ObjectID, Tuple[Optional[bytes], bool]] = {}

    # ------------------------------------------------------------ submit

    def register(self, spec: TaskSpec) -> None:
        with self._lock:
            self._pending[spec.task_id] = spec

    def cancel(self, oid: ObjectID) -> bool:
        """Owner-side cancel: mark so the (already-running) result seals
        TaskCancelledError on arrival. Returns True if it was pending."""
        tid = oid.task_id()
        with self._lock:
            if tid in self._pending:
                self._cancelled.add(tid)
                return True
        return False

    # ------------------------------------------------------------ complete

    def complete(self, task_id: TaskID, err_name: Optional[str],
                 results: List[Tuple[ObjectID, Optional[bytes], bool]]) -> None:
        """Executor reply. ``results`` entries: (oid, inline payload | None
        for store-sealed, is_error)."""
        resubmit = None
        with self._lock:
            spec = self._pending.get(task_id)
            if spec is None:
                return  # stale (superseded attempt)
            cancelled = task_id in self._cancelled
            if err_name is not None and not cancelled and self._retriable(
                    spec, err_name):
                spec.attempt += 1
                resubmit = spec
            else:
                self._pending.pop(task_id, None)
                self._cancelled.discard(task_id)
                if cancelled:
                    err = TaskCancelledError(
                        f"task {task_id.hex()} cancelled")
                    payload = serialization.serialize(err).to_bytes()
                    for oid in spec.return_ids():
                        self._results[oid] = (payload, True)
                elif err_name in _SYSTEM_ERRS and not results:
                    err = WorkerCrashedError(
                        f"direct task {spec.function_name} lost its "
                        f"executor ({err_name}), retries exhausted")
                    payload = serialization.serialize(err).to_bytes()
                    for oid in spec.return_ids():
                        self._results[oid] = (payload, True)
                else:
                    for oid, payload, is_err in results:
                        if oid in self._dropped:
                            self._dropped.discard(oid)
                        else:
                            self._results[oid] = (payload, is_err)
                self._cv.notify_all()
        if resubmit is not None:
            self._submit(resubmit)

    @staticmethod
    def _retriable(spec: TaskSpec, err_name: str) -> bool:
        if spec.attempt >= spec.max_retries:
            return False
        if err_name in _SYSTEM_ERRS:
            return True
        return spec.retry_exceptions

    # ------------------------------------------------------------ reads

    def owns(self, oid: ObjectID) -> bool:
        with self._lock:
            return (oid in self._results
                    or oid.task_id() in self._pending)

    def get_local(self, oid: ObjectID,
                  timeout: Optional[float]) -> Optional[Tuple[Optional[bytes], bool]]:
        """Blocking read of an owned result. Returns (payload|None, is_err),
        or None if this manager does not own the object. A None payload
        means the bytes live in a node store — caller falls through to the
        store path."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while True:
                if oid in self._results:
                    return self._results[oid]
                if oid.task_id() not in self._pending:
                    return None
                # one shared deadline across wakeups: every completion
                # notifies this cv, so a per-wait timeout would restart
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    from .exceptions import GetTimeoutError

                    raise GetTimeoutError(f"get timed out on {oid.hex()}")
                self._cv.wait(remaining)

    def ready_subset(self, oids) -> set:
        """Non-blocking: which of ``oids`` are completed owned results."""
        with self._lock:
            return {o for o in oids if o in self._results}

    def pending_oids(self, oids) -> set:
        """Which of ``oids`` belong to still-pending owned tasks."""
        with self._lock:
            return {o for o in oids if o.task_id() in self._pending}

    def wait_any(self, timeout: Optional[float]) -> None:
        """Block until any completion lands (wait() integration)."""
        with self._lock:
            self._cv.wait(timeout)

    def drop(self, oid: ObjectID) -> None:
        """Owner released its ref: free the retained inline result (or
        mark a still-pending task's result discard-on-arrival)."""
        with self._lock:
            if self._results.pop(oid, None) is None \
                    and oid.task_id() in self._pending:
                self._dropped.add(oid)
