"""Node — per-node daemon state: worker pool, local dispatch, object store.

Analog of the reference's raylet (``src/ray/raylet/node_manager.cc`` +
``worker_pool.cc``): owns the node's shared-memory store, spawns/leases worker
processes, dispatches tasks the cluster scheduler routed here, detects worker
death via connection EOF, and serves worker store/control RPCs (delegating
control-plane ops to the head, as raylets delegate to the GCS). In multi-node
tests several Node objects live in the driver process, each with its own
worker processes and arena — the analog of ``cluster_utils.Cluster`` running
several raylets on one machine.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .config import global_config
from .ids import NodeID, WorkerID
from .object_store import LocalObjectStore
from .protocol import Channel, make_listener
from .resources import NodeResources
from .task_spec import TaskSpec


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    channel: Channel
    pid: int
    proc: Optional[subprocess.Popen] = None
    state: str = "starting"  # starting | idle | busy | actor | dead
    # in-flight plain tasks staged on this worker (lease pipelining:
    # > 1 entry means the next task is already in the worker's memory
    # when the current one finishes); values are
    # (spec, binding, attempt-at-dispatch)
    assigned: Dict[object, Tuple[TaskSpec, dict, int]] = field(
        default_factory=dict)
    actor_id: Optional[object] = None
    reader: Optional[threading.Thread] = None


class Node:
    def __init__(self, head, node_id: NodeID, resources: Dict[str, float],
                 session_dir: str, labels: Optional[Dict[str, str]] = None,
                 node_ip: str = "127.0.0.1"):
        cfg = global_config()
        self.head = head
        self.node_id = node_id
        self.hex = node_id.hex()
        self.session_dir = session_dir
        self.labels = labels or {}
        # routable address of this host, advertised to workers (Train
        # coordinator bootstrap) and in the object-server address; loopback
        # for in-process nodes (reference: raylet node_ip_address)
        self.node_ip = node_ip
        unit_names = set(cfg.unit_instance_resources.split(","))
        self.resources = NodeResources(resources, unit_instance_names=unit_names)
        self.resources.labels = self.labels
        self.store = LocalObjectStore(
            session_dir, self.hex,
            pin_check=lambda oid: head.ref_counts.get(oid, 0) > 0)
        self.max_workers = max(1, int(resources.get("CPU", 1)))
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: deque = deque()
        self._local_queue: deque = deque()  # (spec, binding) waiting for a worker
        self._lock = threading.RLock()
        self._handler_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix=f"node-{self.hex[:6]}"
        )
        self.alive = True
        self._authkey = os.urandom(16)
        self._sock_path = os.path.join(session_dir, f"node_{self.hex[:12]}.sock")
        self._listener = make_listener(self._sock_path, self._authkey)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"accept-{self.hex[:6]}"
        )
        self._accept_thread.start()
        self._num_starting = 0
        self._tail_files: Dict[str, list] = {}  # path -> [offset, pid, dead_ts]
        self._log_tailer_started = False
        # pids spawned but not yet counted down — the countdown happens
        # exactly once, on whichever of (registration, process exit)
        # happens first
        self._starting_pids: set = set()
        with self._lock:
            for _ in range(min(cfg.worker_prestart_count, self.max_workers)):
                self._start_worker_locked()

    # ------------------------------------------------------------ dispatch

    def dispatch(self, spec: TaskSpec, binding: dict) -> None:
        """Called by the cluster scheduler once resources are acquired."""
        with self._lock:
            if not self.alive:
                raise RuntimeError("node is dead")
            self._local_queue.append((spec, binding))
        self._pump()

    def dispatch_to_worker(self, worker_id: WorkerID, spec: TaskSpec) -> bool:
        """Direct dispatch to a specific (actor) worker, bypassing leasing."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None or w.state == "dead":
                return False
        try:
            w.channel.send("exec", pickle.dumps(spec), {})
            return True
        except OSError:
            return False

    def _pump(self) -> None:
        """Match queued tasks with idle workers; start workers as needed.

        When no worker is idle, plain unbound tasks are staged onto a busy
        plain-task worker up to ``worker_pipeline_depth`` deep (reference:
        normal_task_submitter lease pipelining) so the worker starts the
        next task without waiting out the done->dispatch round trip.
        """
        depth = max(1, global_config().worker_pipeline_depth)
        to_send: List[Tuple[WorkerHandle, TaskSpec, dict]] = []
        with self._lock:
            while self._local_queue:
                spec, binding = self._local_queue[0]
                w = None
                while self._idle:
                    cand = self._idle.popleft()
                    if cand.state == "idle":
                        w = cand
                        break
                if w is None:
                    # Prefer starting a new worker while under the limit —
                    # staging must never strand a task behind a long task
                    # when free capacity exists. Queued actor creations
                    # each get a dedicated worker beyond the pool.
                    active = sum(1 for x in self._workers.values()
                                 if x.state in ("idle", "busy")) + self._num_starting
                    limit = self.max_workers + sum(
                        1 for s, _ in self._local_queue if s.is_actor_creation)
                    if active < limit:
                        self._start_worker_locked()
                        break
                    # at capacity: stage onto a busy plain-task worker
                    if not spec.is_actor_creation and not binding:
                        for cand in self._workers.values():
                            if (cand.state == "busy"
                                    and len(cand.assigned) < depth
                                    and all(not s.is_actor_creation and not b
                                            for s, b, _ in
                                            cand.assigned.values())):
                                w = cand
                                break
                    if w is None:
                        break
                self._local_queue.popleft()
                w.state = "busy"
                # stamp the attempt at assignment: spec objects are shared
                # with the head and mutate on retry, so a late finish must
                # carry the attempt it actually ran
                w.assigned[spec.task_id] = (spec, binding, spec.attempt)
                to_send.append((w, spec, binding))
            # rescue: a worker sits idle with nothing queued while another
            # has staged-unstarted tasks — ask for one back so it isn't
            # stuck behind a long/blocked task. (Not triggered by workers
            # merely starting, and never for tasks staged in this call —
            # both would ping-pong stage/unstage.)
            unstage: List[Tuple[WorkerHandle, object]] = []
            just_staged = {spec.task_id for _, spec, _ in to_send}
            if not self._local_queue and self._idle:
                for cand in self._workers.values():
                    if cand.state == "busy" and len(cand.assigned) > 1:
                        last_tid = next(reversed(cand.assigned))
                        if last_tid not in just_staged:
                            unstage.append((cand, last_tid))
        for w, spec, binding in to_send:
            try:
                w.channel.send("exec", pickle.dumps(spec), binding)
            except OSError:
                self._on_worker_dead(w)
        for w, tid in unstage:
            try:
                w.channel.send("unstage", tid)
            except OSError:
                self._on_worker_dead(w)

    # ------------------------------------------------------------ workers

    def _start_worker_locked(self) -> None:
        self._num_starting += 1
        env = dict(os.environ)
        env["RAY_TPU_NODE_HEX"] = self.hex
        if self.resources.total.get("TPU") == 0:
            # CPU-only node: skip the TPU plugin registration in sitecustomize
            # (it imports jax, ~2s per process start)
            env.pop("PALLAS_AXON_POOL_IPS", None)
        # make ray_tpu importable in the worker regardless of driver cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        log_path = os.path.join(self.session_dir, "logs")
        os.makedirs(log_path, exist_ok=True)
        log_file = os.path.join(log_path, f"worker-{time.time_ns()}.log")
        out = open(log_file, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_runtime",
             "--address", self._sock_path, "--authkey", self._authkey.hex()],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            cwd=os.getcwd(),
        )
        self._starting_pids.add(proc.pid)
        self._tail_files[log_file] = [0, proc.pid, None]
        self._ensure_log_tailer()
        # handle registered on accept
        threading.Thread(
            target=self._reap, args=(proc,), daemon=True
        ).start()

    def _reap(self, proc: subprocess.Popen) -> None:
        proc.wait()
        # a worker that died before registering would leak _num_starting
        # (and with it a phantom slot in _pump's active count) forever
        with self._lock:
            if proc.pid in self._starting_pids:
                self._starting_pids.discard(proc.pid)
                self._num_starting = max(0, self._num_starting - 1)
            for st in self._tail_files.values():
                if st[1] == proc.pid and st[2] is None:
                    st[2] = time.monotonic()  # tailer drops it after a
                    # final read window

    def _accept_loop(self) -> None:
        import multiprocessing.context as _mpctx

        while self.alive:
            try:
                conn = self._listener.accept()
            except _mpctx.AuthenticationError:
                # worker killed mid-handshake (node/cluster shutdown race)
                continue
            except (OSError, EOFError):
                return
            channel = Channel(conn)
            try:
                tag, (pid,) = channel.recv()
                assert tag == "register"
            except Exception:
                channel.close()
                continue
            wid = WorkerID.from_random()
            w = WorkerHandle(worker_id=wid, channel=channel, pid=pid, state="idle")
            with self._lock:
                if pid in self._starting_pids:
                    self._starting_pids.discard(pid)
                    self._num_starting = max(0, self._num_starting - 1)
                self._workers[wid] = w
                self._idle.append(w)
            init_info = {
                "worker_id": wid.binary(),
                "node_hex": self.hex,
                "node_ip": self.node_ip,
                "job_id": self.head.job_id.binary(),
                "arena_path": self.store.arena_path,
                "arena_capacity": self.store.capacity,
                "config": global_config().to_json(),
            }
            channel.send("init", init_info)
            w.reader = threading.Thread(
                target=self._reader_loop, args=(w,), daemon=True,
                name=f"reader-{wid.hex()[:6]}",
            )
            w.reader.start()
            self._pump()

    def _reader_loop(self, w: WorkerHandle) -> None:
        while True:
            try:
                tag, payload = w.channel.recv()
            except (EOFError, OSError):
                self._on_worker_dead(w)
                return
            if tag == "done":
                task_id, results, err_name = payload
                self._on_task_done(w, task_id, results, err_name)
            elif tag == "store":
                req_id, op, *args = payload
                if op in ("get", "wait", "create"):
                    self._handler_pool.submit(self._handle_store, w, req_id, op, args)
                else:
                    self._handle_store(w, req_id, op, args)
            elif tag == "rpc":
                req_id, op, *args = payload
                self._handler_pool.submit(self._handle_rpc, w, req_id, op, args)
            elif tag == "release":
                for oid in payload[0]:
                    self.store.remove_ref(oid)
            elif tag == "stream":
                self.head.on_stream_item(*payload)
            elif tag == "metrics":
                self.head.on_worker_metrics(
                    f"{self.hex[:6]}:{w.pid}", payload[0])
            elif tag == "unstaged":
                # worker handed back a staged-unstarted task: requeue it
                tid = payload[0]
                with self._lock:
                    entry = w.assigned.pop(tid, None)
                    if entry is not None:
                        self._local_queue.appendleft(entry[:2])
                        if w.state == "busy" and not w.assigned:
                            w.state = "idle"
                            self._idle.append(w)
                if entry is not None:
                    self._pump()
            elif tag == "exit":
                # graceful actor exit
                self._on_worker_exit(w)
                return

    def _reply(self, w: WorkerHandle, req_id: int, ok: bool, value) -> None:
        try:
            w.channel.send("rep", req_id, ok, value)
        except OSError:
            pass

    def _handle_store(self, w: WorkerHandle, req_id: int, op: str, args) -> None:
        try:
            if op == "get":
                oid, timeout = args
                rep = self.head.get_object_for_node(self, oid, timeout)
                self._reply(w, req_id, True, rep)
            elif op == "wait":
                oids, num_returns, timeout = args
                ready = self.head.wait_objects(oids, num_returns, timeout)
                self._reply(w, req_id, True, ready)
            elif op == "create":
                oid, size = args
                offset, _ = self.store.create(oid, size)
                self._reply(w, req_id, True, offset)
            elif op == "seal":
                oid, is_error = args
                self.store.seal(oid, is_error)
                self.head.on_object_sealed(oid, self.hex)
                self._reply(w, req_id, True, None)
            elif op == "put_inline":
                oid, data, is_error = args
                self.store.put_inline(oid, data, is_error)
                self.head.on_object_sealed(oid, self.hex)
                self._reply(w, req_id, True, None)
            else:
                self._reply(w, req_id, False, ValueError(f"bad store op {op}"))
        except Exception as e:  # noqa: BLE001
            self._reply(w, req_id, False, e)

    def _handle_rpc(self, w: WorkerHandle, req_id: int, op: str, args) -> None:
        try:
            result = self.head.handle_worker_rpc(self, w, op, args)
            self._reply(w, req_id, True, result)
        except Exception as e:  # noqa: BLE001
            self._reply(w, req_id, False, e)

    # ------------------------------------------------------------ lifecycle

    def _on_task_done(self, w: WorkerHandle, task_id, results, err_name) -> None:
        with self._lock:
            entry = w.assigned.pop(task_id, None)
            if entry is not None:
                spec, binding, attempt = entry
                if spec.is_actor_creation and err_name is None:
                    w.state = "actor"
                    w.actor_id = spec.actor_id
                elif w.state == "busy" and not w.assigned:
                    w.state = "idle"
                    self._idle.append(w)
            else:
                # actor task done (worker stays "actor") or stale
                spec, binding, attempt = None, None, None
        # The head decides whether to seal results (it may retry instead).
        self.head.on_task_finished(self, task_id, err_name, spec, binding,
                                   results, worker_id=w.worker_id,
                                   attempt=attempt)
        self._pump()

    def _on_worker_exit(self, w: WorkerHandle) -> None:
        with self._lock:
            w.state = "dead"
            self._workers.pop(w.worker_id, None)
        self.head.on_worker_exit(self, w)

    def _on_worker_dead(self, w: WorkerHandle) -> None:
        with self._lock:
            if w.state == "dead":
                return
            prev_state = w.state
            w.state = "dead"
            self._workers.pop(w.worker_id, None)
            assigned = list(w.assigned.values())
            w.assigned.clear()
        w.channel.close()
        if assigned:
            for spec, binding, _attempt in assigned:
                self.head.on_worker_crashed(self, w, spec, binding, prev_state)
        else:
            self.head.on_worker_crashed(self, w, None, None, prev_state)
        self._pump()

    def cancel_task(self, task_id, worker_id: Optional[WorkerID],
                    force: bool) -> None:
        """Forward a cancel to the worker running ``task_id`` (or the given
        actor worker). Reference: CoreWorker::CancelTask -> executor interrupt."""
        with self._lock:
            target = None
            if worker_id is not None:
                target = self._workers.get(worker_id)
            else:
                for w in self._workers.values():
                    if task_id in w.assigned:
                        target = w
                        break
        if target is None:
            return
        try:
            target.channel.send("cancel", task_id)
        except OSError:
            pass
        if force:
            self.kill_worker(target.worker_id)

    def _ensure_log_tailer(self) -> None:
        """Tail worker log files -> head -> driver stderr (reference:
        log_monitor.py:581 tails per-proc files to the driver)."""
        if self._log_tailer_started or not global_config().log_to_driver:
            return
        self._log_tailer_started = True

        def tail():
            while self.alive:
                now = time.monotonic()
                for path, st in list(self._tail_files.items()):
                    try:
                        with open(path, "rb") as f:
                            f.seek(st[0])
                            data = f.read()
                    except OSError:
                        self._tail_files.pop(path, None)
                        continue
                    if data:
                        st[0] += len(data)
                        try:
                            self.head.on_worker_log(
                                self.hex, st[1],
                                data.decode("utf-8", "replace"))
                        except Exception:
                            pass
                    if st[2] is not None and now - st[2] > 2.0:
                        self._tail_files.pop(path, None)  # worker gone
                time.sleep(0.5)

        threading.Thread(target=tail, daemon=True,
                         name=f"logtail-{self.hex[:6]}").start()

    def start_object_server(self, authkey: bytes, host: Optional[str] = None):
        """Start the node-to-node chunk server (multi-host mode).

        Binds all interfaces when the node has a non-loopback ``node_ip``
        and advertises that IP, so cross-host pulls get a routable address.
        """
        from .object_transfer import ObjectServer

        if getattr(self, "object_server", None) is None:
            if host is None:
                host = ("127.0.0.1" if self.node_ip.startswith("127.")
                        else "0.0.0.0")
            self.object_server = ObjectServer(
                self.store, authkey, host,
                advertise_host=self.node_ip)
        return self.object_server

    def kill_worker(self, worker_id: WorkerID) -> None:
        with self._lock:
            w = self._workers.get(worker_id)
        if w is None:
            return
        try:
            w.channel.send("shutdown")
        except OSError:
            pass
        try:
            os.kill(w.pid, 9)
        except (OSError, ProcessLookupError):
            pass

    def num_workers(self) -> int:
        with self._lock:
            return len(self._workers)

    def shutdown(self) -> None:
        self.alive = False
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            try:
                w.channel.send("shutdown")
            except OSError:
                pass
            try:
                os.kill(w.pid, 9)
            except (OSError, ProcessLookupError):
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        if getattr(self, "object_server", None) is not None:
            self.object_server.close()
        self.store.close()
        self._handler_pool.shutdown(wait=False)
