"""Node — per-node daemon state: worker pool, local dispatch, object store.

Analog of the reference's raylet (``src/ray/raylet/node_manager.cc`` +
``worker_pool.cc``): owns the node's shared-memory store, spawns/leases worker
processes, dispatches tasks the cluster scheduler routed here, detects worker
death via connection EOF, and serves worker store/control RPCs (delegating
control-plane ops to the head, as raylets delegate to the GCS). In multi-node
tests several Node objects live in the driver process, each with its own
worker processes and arena — the analog of ``cluster_utils.Cluster`` running
several raylets on one machine.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import fault_injection
from .config import global_config
from .ids import NodeID, ObjectID, WorkerID
from .object_store import LocalObjectStore
from .protocol import Channel, make_listener
from .resources import NodeResources
from .task_spec import TaskSpec


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    channel: Channel
    pid: int
    proc: Optional[subprocess.Popen] = None
    state: str = "starting"  # starting | idle | busy | actor | dead
    # in-flight plain tasks staged on this worker (lease pipelining:
    # > 1 entry means the next task is already in the worker's memory
    # when the current one finishes); values are
    # (spec, binding, attempt-at-dispatch)
    assigned: Dict[object, Tuple[TaskSpec, dict, int]] = field(
        default_factory=dict)
    actor_id: Optional[object] = None
    reader: Optional[threading.Thread] = None


class Node:
    def __init__(self, head, node_id: NodeID, resources: Dict[str, float],
                 session_dir: str, labels: Optional[Dict[str, str]] = None,
                 node_ip: str = "127.0.0.1"):
        cfg = global_config()
        self.head = head
        self.node_id = node_id
        self.hex = node_id.hex()
        self.session_dir = session_dir
        self.labels = labels or {}
        # routable address of this host, advertised to workers (Train
        # coordinator bootstrap) and in the object-server address; loopback
        # for in-process nodes (reference: raylet node_ip_address)
        self.node_ip = node_ip
        unit_names = set(cfg.unit_instance_resources.split(","))
        self.resources = NodeResources(resources, unit_instance_names=unit_names)
        self.resources.labels = self.labels
        self.store = LocalObjectStore(
            session_dir, self.hex,
            pin_check=self._store_pin_check,
            # daemons only see the local holder lease (no head pin view):
            # their stores must spill — never evict — primary copies
            pin_check_authoritative=hasattr(head, "nodes"))
        self.max_workers = max(1, int(resources.get("CPU", 1)))
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: deque = deque()
        self._local_queue: deque = deque()  # (spec, binding) waiting for a worker
        # pending node->worker stack-dump rounds (collect_worker_stacks):
        # req_id -> [event, reply, worker_id]
        self._stack_seq = 0
        self._stack_pending: Dict[int, list] = {}
        from .lock_debug import tracked_rlock

        self._lock = tracked_rlock("Node._lock")
        self._handler_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix=f"node-{self.hex[:6]}"
        )
        self.alive = True
        # set by shutdown(); paced loops (steal ticker) wait on it so
        # they exit the instant the node dies instead of a sleep later
        self._stop_event = threading.Event()
        self._authkey = os.urandom(16)
        self._sock_path = os.path.join(session_dir, f"node_{self.hex[:12]}.sock")
        self._listener = make_listener(self._sock_path, self._authkey)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"accept-{self.hex[:6]}"
        )
        self._accept_thread.start()
        self._num_starting = 0
        self._tail_files: Dict[str, list] = {}  # path -> [offset, pid, dead_ts]
        self._log_tailer_started = False
        # pids spawned but not yet counted down — the countdown happens
        # exactly once, on whichever of (registration, process exit)
        # happens first
        self._starting_pids: set = set()
        # ---- direct (head-bypass) task path state -----------------------
        # Holder-side owner leases: while a direct task is in flight
        # through this node (queued locally or forwarded to a peer), its
        # pinned ref args may not be evicted from — or deleted out of —
        # the local store. The lease is the holder's half of the OWNER'S
        # arg pin (DirectTaskManager._pin_counts) and releases on the
        # same reply chain that settles the task; no head RPC is involved
        # (replaces the old per-task pin_delta / is_pinned head ops).
        self._arg_leases: Dict[ObjectID, int] = {}
        self._leased_tasks: Dict[object, tuple] = {}
        self._deferred_deletes: set = set()
        # locally-executing direct tasks: task_id -> (origin, spec)
        self._direct: Dict[object, Tuple[tuple, TaskSpec]] = {}
        # stream-item oids sealed locally for a direct streaming task;
        # they ride the task's completion devent so the head's object
        # directory learns their location in one batched report
        self._direct_stream_oids: Dict[object, List[ObjectID]] = {}
        # actors hosted on this node: actor_id -> worker_id (the routing
        # table for direct actor calls; reference: the actor's RPC address
        # cached by ActorTaskSubmitter)
        self._actor_workers: Dict[object, WorkerID] = {}
        # tasks forwarded to a peer: task_id -> (origin, spec, peer_hex)
        self._forwarded: Dict[object, Tuple[tuple, TaskSpec, str]] = {}
        self._peers: Dict[str, Channel] = {}      # peer_hex -> channel
        # optimistic in-flight counts per peer: reported queue depths lag
        # by a syncer period, so without this a submission burst dogpiles
        # whichever peer last reported the lowest load
        self._peer_inflight: Dict[str, int] = {}
        # peer-gossiped load: hex -> (version, queue_depth, recv_ts).
        # Fresh entries overlay the head's cluster-view queue numbers,
        # which lag by a report period (reference: RaySyncer peer bidi
        # streams vs star rebroadcast — round-3 audit weak #10)
        self._peer_loads: Dict[str, tuple] = {}
        self._gossip_version = 0
        self._peer_lock = threading.Lock()
        self._peer_key: Optional[bytes] = None    # set by start_object_server
        # stream_sub round-trips in flight: req_id -> [Event, reply,
        # owner_worker_id | None] (replies arrive as "srep" from a local
        # owner worker or "psubrep" from a peer node)
        self._ssub_pending: Dict[int, list] = {}
        self._ssub_seq = 0
        self._ssub_lock = threading.Lock()
        self._devents: List[tuple] = []           # batched head event reports
        self._dev_lock = threading.Lock()
        self._dev_first: float = 0.0
        self._dev_flusher_started = False
        with self._lock:
            for _ in range(min(cfg.worker_prestart_count, self.max_workers)):
                self._start_worker_locked()
            self._ensure_prewarm_locked()
        self._steal_thread = None
        if cfg.direct_steal_enabled:
            # idle nodes get no pump events: a slow heartbeat re-evaluates
            # stealing (rate-limited + cheap-idle-checked inside)
            self._steal_thread = threading.Thread(
                target=self._steal_ticker, daemon=True,
                name=f"steal-{self.hex[:6]}")
            self._steal_thread.start()

    # ------------------------------------------------------------ dispatch

    def dispatch(self, spec: TaskSpec, binding: dict) -> None:
        """Called by the cluster scheduler once resources are acquired."""
        with self._lock:
            if not self.alive:
                raise RuntimeError("node is dead")
            self._local_queue.append((spec, binding))
        self._pump()

    def dispatch_to_worker(self, worker_id: WorkerID, spec: TaskSpec) -> bool:
        """Direct dispatch to a specific (actor) worker, bypassing leasing."""
        # chaos point: "node.dispatch_worker=fail@N" bounces this dispatch
        # as if the worker were already gone (provably-undelivered path)
        if fault_injection.fire("node.dispatch_worker") == "fail":
            return False
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None or w.state == "dead":
                return False
        try:
            w.channel.send("exec", pickle.dumps(spec), {})
            return True
        except OSError:
            return False

    # ---------------------------------------------------- direct task path
    # (reference: normal_task_submitter.cc — submitter leases from its
    # LOCAL raylet and pushes directly; the GCS sees only async events)

    def submit_direct(self, spec: TaskSpec, origin: tuple) -> None:
        """Execute an eligible plain task (or route an actor call)
        without head involvement.

        ``origin`` routes the completion reply:
          ("worker", worker_id)      — a worker on this node submitted it
          ("driver", done_cb, stream_cb) — the in-process driver submitted it
          ("peer", channel)          — a peer node forwarded it here
          ("node", node, inner)      — in-process peer hop: reply via node
        """
        if not self.alive:
            self._reply_direct(origin, spec.task_id, "NodeDiedError", [])
            return
        if spec.actor_id is not None and not spec.is_actor_creation:
            self._submit_direct_actor(spec, origin)
            return
        if spec.direct_hops == 0:
            # locality first (reference: lease_policy.h:56
            # LocalityAwareLeasePolicy — lease from the node holding the
            # largest args): store-resident args are >100KB by definition
            # while inline args ride in the spec, so the node hinted by
            # the most store-resident args holds the most arg bytes.
            loc = self._locality_target(spec)
            if loc is not None and self._forward_direct(spec, origin, loc):
                return
        if spec.direct_hops <= 1 and self._maybe_spill(spec, origin):
            # hop cap 2 (locality + one spill): a saturated arg-holder
            # node sheds locality-forwarded fan-out to its peers instead
            # of serializing the whole wave (reference: spillback applies
            # at the lease target too)
            return
        with self._lock:
            self._direct[spec.task_id] = (origin, spec, time.time())
            self._lease_args_locked(spec)
        self._ensure_direct_flusher()
        try:
            self.dispatch(spec, {})
        except RuntimeError:
            with self._lock:
                self._direct.pop(spec.task_id, None)
            self._task_departed(spec.task_id)
            self._reply_direct(origin, spec.task_id, "NodeDiedError", [])

    def _finish_direct(self, origin: tuple, spec: TaskSpec, task_id,
                       results, err_name: Optional[str],
                       t_start: Optional[float] = None) -> None:
        """Executor-side completion: seal inline results locally, batch the
        event report to the head, reply straight to the owner."""
        sealed = []
        for oid, payload, is_err in results:
            if payload is not None:
                try:
                    self.store.put_inline(oid, payload, is_err)
                    sealed.append(oid)
                except Exception:
                    # store full: the owner still gets the inline payload,
                    # but head-path consumers (ref args, borrowers) need a
                    # resolvable location — seal in the head store instead
                    try:
                        self.head.on_sealed_payload(oid, payload, is_err)
                    except Exception:
                        pass
        with self._lock:
            stream_oids = self._direct_stream_oids.pop(task_id, None)
        if stream_oids:
            sealed.extend(stream_oids)
        self._append_devent(spec, err_name, sealed, t_start)
        self._reply_direct(origin, task_id, err_name, results, self.hex)

    def _reply_stream_item(self, origin: tuple, task_id, index: int,
                           data: Optional[bytes],
                           exec_hex: Optional[str]) -> None:
        """Route a stream-item announcement back along the same chain as
        the eventual completion reply (FIFO on every hop, so the owner
        always sees items before the final ddone)."""
        kind = origin[0]
        try:
            if kind == "worker":
                with self._lock:
                    w = self._workers.get(origin[1])
                if w is not None:
                    w.channel.send("dstream", task_id, index, data,
                                   exec_hex)
            elif kind == "driver":
                origin[2](task_id, index, data, exec_hex)
            elif kind == "peer":
                origin[1].send("pstream", task_id, index, data, exec_hex)
            elif kind == "node":
                origin[1]._reply_stream_item(origin[2], task_id, index,
                                             data, exec_hex)
        except (OSError, EOFError):
            pass  # owner gone: items die with it (owner-died semantics)

    def _reply_direct(self, origin: tuple, task_id, err_name,
                      results, exec_hex: Optional[str] = None) -> None:
        kind = origin[0]
        try:
            if kind == "worker":
                with self._lock:
                    w = self._workers.get(origin[1])
                if w is not None:
                    w.channel.send("ddone", task_id, err_name, results,
                                   exec_hex)
            elif kind == "driver":
                origin[1](task_id, err_name, results, exec_hex)
            elif kind == "peer":
                origin[1].send("pdone", task_id, err_name, results, exec_hex)
            elif kind == "node":
                peer = origin[1]
                with peer._lock:
                    peer._forwarded.pop(task_id, None)
                peer._task_departed(task_id)
                peer._reply_direct(origin[2], task_id, err_name, results,
                                   exec_hex)
        except (OSError, EOFError):
            pass  # owner gone: its results die with it (owner-died semantics)

    def _submit_direct_actor(self, spec: TaskSpec, origin: tuple) -> None:
        """Route a direct actor call: dispatch to the local actor worker,
        or forward one hop to the node the owner believes hosts the actor
        (reference: ActorTaskSubmitter::PushActorTask — caller to actor
        process, the control plane never sees the call)."""
        with self._lock:
            wid = self._actor_workers.get(spec.actor_id)
        if wid is not None:
            with self._lock:
                self._direct[spec.task_id] = (origin, spec, time.time())
                self._lease_args_locked(spec)
            self._ensure_direct_flusher()
            if not self.dispatch_to_worker(wid, spec):
                with self._lock:
                    self._direct.pop(spec.task_id, None)
                self._task_departed(spec.task_id)
                # delivery provably failed (worker gone or send raised
                # before the call hit the wire): a location error — the
                # owner re-resolves and resubmits without consuming the
                # max_task_retries budget (never-executed is always safe)
                self._reply_direct(origin, spec.task_id,
                                   "ActorMissingError", [])
            return
        target = spec.actor_node_hex
        if (target is None or target == self.hex or origin[0] == "peer"
                or spec.direct_hops >= 1
                or not self._forward_direct(spec, origin, target)):
            # stale owner location (or already forwarded once, or the
            # peer is unreachable): bounce so the owner re-resolves via
            # the head's actor FSM
            self._reply_direct(origin, spec.task_id, "ActorMissingError", [])

    def _forward_direct(self, spec: TaskSpec, origin: tuple,
                        target: str) -> bool:
        """Ship a direct task one hop to ``target``'s node (actor routing,
        locality dispatch, spillback all ride this). False = unreachable
        (caller decides the fallback)."""
        handle = self._peer_handle_for(target)
        if handle is None:
            return False
        spec.direct_hops = 1
        if not isinstance(handle, tuple):
            # in-process peer Node
            with self._lock:
                self._forwarded[spec.task_id] = (origin, spec, handle)
                self._lease_args_locked(spec)
            handle.submit_direct(spec, ("node", self, origin))
            return True
        ch = self._peer_channel(target, handle)
        if ch is None:
            spec.direct_hops = 0
            return False
        with self._lock:
            self._forwarded[spec.task_id] = (origin, spec, target)
            self._lease_args_locked(spec)
        try:
            ch.send("psubmit", pickle.dumps(spec))
        except (OSError, EOFError):
            with self._lock:
                self._forwarded.pop(spec.task_id, None)
            self._task_departed(spec.task_id)
            self._drop_peer(target)
            spec.direct_hops = 0
            return False
        return True

    def _locality_target(self, spec: TaskSpec) -> Optional[str]:
        """Peer node holding the most store-resident args, if not us.
        An explicit ``spec.locality_hex`` (caller-provided hint, e.g. the
        data executor targeting a block holder) is the fallback when the
        arg hints don't name a node — small blocks ride inline and leave
        no store hint, but the caller still knows where they live."""
        hints = spec.arg_hints
        counts: Dict[str, int] = {}
        for h in (hints or {}).values():
            if h[0] == "node":
                counts[h[1]] = counts.get(h[1], 0) + 1
        if counts:
            best = max(counts, key=lambda k: counts[k])
        else:
            best = spec.locality_hex
        if best is None or best == self.hex:
            return None
        # don't ship work to a node we can't see or that already left
        return best

    def _peer_handle_for(self, peer_hex: str):
        """Node object (in-process) or (host, port) for a peer's object/
        control server, from the head table or the syncer cluster view."""
        head = self.head
        if hasattr(head, "nodes"):  # in-process side
            n = head.nodes.get(peer_hex)
            if n is None:
                return None
            if hasattr(n, "store"):
                return n
            return tuple(n.object_addr)
        for e in head.cluster_view:
            if e.get("hex") == peer_hex and e.get("addr"):
                return tuple(e["addr"])
        return None

    def cancel_direct(self, task_id, force: bool = False) -> None:
        """Owner-initiated cancel of a direct task: drop it from the local
        queue if not started, interrupt the worker if running, or forward
        the cancel to the peer executing it (reference:
        CoreWorker::CancelTask -> executor interrupt)."""
        peer_hex = None
        with self._lock:
            fwd = self._forwarded.get(task_id)
            if fwd is not None:
                peer_hex = fwd[2]
            elif task_id in self._direct:
                for i, (spec, binding) in enumerate(self._local_queue):
                    if spec.task_id == task_id:
                        del self._local_queue[i]
                        origin, spec, _t = self._direct.pop(task_id)
                        break
                else:
                    origin = None
            else:
                return
        if peer_hex is not None:
            if isinstance(peer_hex, tuple) and peer_hex[0] == "_stolen":
                # stolen over TCP: the victim's server conn is duplex —
                # forward the cancel to the thief
                try:
                    peer_hex[1].send("pcancel", task_id, force)
                except (OSError, EOFError):
                    pass
                return
            if not isinstance(peer_hex, str):
                # in-process peer Node: cancel it there directly
                peer_hex.cancel_direct(task_id, force)
                return
            with self._peer_lock:
                ch = self._peers.get(peer_hex)
            if ch is not None:
                try:
                    ch.send("pcancel", task_id, force)
                except (OSError, EOFError):
                    pass
            return
        if origin is not None:  # was still queued: never ran
            self._task_departed(task_id)
            self._reply_direct(origin, task_id, "TaskCancelledError", [])
            return
        # running (or staged) on a worker: interrupt it. Actor calls are
        # not in w.assigned — route the cancel via the actor index (and
        # never force-kill: that would kill the actor, not the call).
        with self._lock:
            entry = self._direct.get(task_id)
            awid = (self._actor_workers.get(entry[1].actor_id)
                    if entry is not None and entry[1].actor_id is not None
                    else None)
        if awid is not None:
            self.cancel_task(task_id, awid, False)
        else:
            self.cancel_task(task_id, None, force)

    # ---- holder-side owner leases ---------------------------------------
    # (the node-local half of owner-side arg pinning: no head traffic)

    def _lease_args_locked(self, spec: TaskSpec) -> None:
        """Take a store lease on the task's pinned ref args (idempotent
        per task). Caller holds self._lock."""
        if not spec.pinned_args or spec.task_id in self._leased_tasks:
            return
        self._leased_tasks[spec.task_id] = tuple(spec.pinned_args)
        for oid in spec.pinned_args:
            self._arg_leases[oid] = self._arg_leases.get(oid, 0) + 1

    def _task_departed(self, task_id) -> None:
        """A direct task left this node (settled, forwarded away and
        replied, or failed): release its arg leases, apply any store
        deletes that were deferred while the lease was held, and let an
        in-process head retry a cluster-wide delete it deferred behind
        this lease."""
        to_delete = []
        released = []
        with self._lock:
            if task_id in self._direct or task_id in self._forwarded:
                return  # still tracked under the other map
            oids = self._leased_tasks.pop(task_id, None)
            if not oids:
                return
            for oid in oids:
                n = self._arg_leases.get(oid, 0) - 1
                if n > 0:
                    self._arg_leases[oid] = n
                else:
                    self._arg_leases.pop(oid, None)
                    released.append(oid)
                    if oid in self._deferred_deletes:
                        self._deferred_deletes.discard(oid)
                        to_delete.append(oid)
        for oid in to_delete:
            try:
                self.store.delete(oid)
            except Exception:
                pass
        if released and hasattr(self.head, "release_holder_lease"):
            # in-process head: retry cluster-wide deletes deferred behind
            # this node's lease (daemon-side leases only guard their own
            # store; the daemon's copy is the one the lease protects)
            try:
                self.head.release_holder_lease(released)
            except Exception:
                pass

    def replay_snapshot(self) -> dict:
        """What this node replays to a RESTARTED head at re-registration
        (node_daemon rejoin): the store manifest (rebuilds the object
        directory), live holder leases (re-guards deferred deletes), and
        hosted actors (revives their ALIVE records + routing). All of it
        is node-resident state the head merely mirrors — the same tables
        the 1 s syncer keeps fresh, shipped once, in full."""
        with self._lock:
            actors = list(self._actor_workers.items())
            leases = list(self._arg_leases.keys())
        objects = [row[0] for row in self.store.object_infos()]
        return {"objects": objects, "leases": leases, "actors": actors}

    def has_lease(self, oid: ObjectID) -> bool:
        """Lock-free: an in-flight direct task through this node leases
        ``oid`` (consulted by the in-process head's delete decisions)."""
        return self._arg_leases.get(oid, 0) > 0

    def lease_snapshot(self) -> list:
        """Current leased arg oids (piggybacked on the daemon's periodic
        sync snapshot so the HEAD's delete decisions can defer behind a
        daemon-held lease without any per-task wire traffic; staleness is
        one sync period — the same window the old one-way pin_delta
        messages had in flight). Never truncated: a dropped lease would
        silently disable delete protection, so an abnormally large set
        only costs a bigger sync message (and warns once per minute)."""
        with self._lock:
            leases = list(self._arg_leases.keys())
        if len(leases) > 4096:
            now = time.monotonic()
            if now - getattr(self, "_lease_warn_ts", 0.0) > 60.0:
                self._lease_warn_ts = now
                from ray_tpu.util import events as events_mod

                events_mod.emit(
                    "WARNING", events_mod.SOURCE_NODE,
                    f"node {self.hex[:8]} holds {len(leases)} in-flight "
                    "arg leases; sync snapshots are growing large",
                    entity_id=self.hex, leases=len(leases))
        return leases

    def _store_pin_check(self, oid: ObjectID) -> bool:
        """Store eviction guard: leased args, head-path pins, and the
        driver's owner-side pins all protect an object. Lock-free dict
        reads (same benign-race contract the head ref_counts check had);
        daemons have no head tables and rely on the local lease alone."""
        if self._arg_leases.get(oid, 0) > 0:
            return True
        rc = getattr(self.head, "ref_counts", None)
        if rc is not None and rc.get(oid, 0) > 0:
            return True
        epc = getattr(self.head, "extra_pin_check", None)
        if epc is not None:
            try:
                return bool(epc(oid))
            except Exception:
                return True  # fail pinned: never evict on a glitch
        return False

    def delete_from_store(self, oid: ObjectID) -> None:
        """Store deletion that honors holder leases: while an in-flight
        direct task leases ``oid``, the delete is deferred until the
        lease releases (owner-release-then-delete ordering)."""
        with self._lock:
            if self._arg_leases.get(oid, 0) > 0:
                self._deferred_deletes.add(oid)
                return
        self.store.delete(oid)

    # ---- stream subscriptions (owner-side published streams) -------------
    # A consumer holding a serialized generator handle subscribes to the
    # OWNER along the worker<->node<->peer reply channels; the head is
    # never involved (reference: streaming generator reports are
    # submitter-side, core_worker.h TryReadObjectRefStream).

    def _ssub_slot(self, worker_id=None):
        with self._ssub_lock:
            self._ssub_seq += 1
            req_id = self._ssub_seq
            slot = [threading.Event(), None, worker_id]
            self._ssub_pending[req_id] = slot
        return req_id, slot

    def _ssub_reply(self, req_id: int, rep) -> None:
        with self._ssub_lock:
            slot = self._ssub_pending.pop(req_id, None)
        if slot is not None:
            slot[1] = rep
            slot[0].set()

    def _fail_worker_ssubs(self, worker_id, pid=None) -> None:
        """The owner worker died: its parked subscribers learn now."""
        from .exceptions import format_death_cause

        with self._ssub_lock:
            gone = [(rid, s) for rid, s in self._ssub_pending.items()
                    if s[2] == worker_id]
            for rid, _s in gone:
                self._ssub_pending.pop(rid, None)
        cause = format_death_cause("stream owner worker died", self.hex, pid)
        for _rid, slot in gone:
            slot[1] = ("gone", cause)
            slot[0].set()

    def serve_stream_sub(self, owner, task_id, index: int,
                         timeout: float):
        """One bounded subscription round against the stream's owner.
        Routes: driver-owned -> the driver's manager (in-process hook or
        peer hop to the head node); worker-owned -> the owner worker via
        its node (local ``ssub`` round-trip or peer ``psub`` hop).
        Inline item payloads are sealed into THIS node's store before the
        reply so the consumer's get resolves locally."""
        rep = self._route_stream_sub(owner, task_id, index, timeout)
        if rep is None:
            rep = ("gone", "stream owner no longer holds the stream")
        # not a wire-op ladder: rep is stream_next_remote's RETURN tuple
        # (in-process call or already-framed psubrep payload)
        # graftlint: ignore[protocol-completeness]
        if rep[0] == "item" and len(rep) > 2 and rep[2] is not None:
            oid, payload = rep[1], rep[2]
            sealed = False
            try:
                if not self.store.contains(oid):
                    self.store.put_inline(oid, payload, False,
                                          transfer=True)
                    if hasattr(self.head, "nodes"):
                        # in-process: registering the cache copy is a
                        # method call (daemons skip — no per-item sends)
                        self.head.on_object_sealed(oid, self.hex)
                sealed = True
            except Exception:
                pass  # store full: fall back to the executor-node hint
            # keep the owner's location hint when the local seal failed —
            # inline items also have a store copy at the executor node
            return ("item", oid,
                    None if sealed else (rep[3] if len(rep) > 3 else None))
        # graftlint: ignore[protocol-completeness]
        if rep[0] == "item":
            return ("item", rep[1], rep[3] if len(rep) > 3 else None)
        # graftlint: ignore[protocol-completeness]
        if rep[0] == "error" and len(rep) > 1 and rep[1] is not None:
            # owner-sealed failure: seal the primary's error payload
            # locally so the consumer's follow-up get can raise it
            try:
                prim = ObjectID.for_task_return(task_id, 0)
                if not self.store.contains(prim):
                    self.store.put_inline(prim, rep[1], True,
                                          transfer=True)
            except Exception:
                pass
            return ("error",)
        return rep

    def _route_stream_sub(self, owner, task_id, index, timeout):
        kind = owner[0] if owner else None
        head = self.head
        in_process = hasattr(head, "nodes")
        if kind == "d":
            if in_process:
                hook = getattr(head, "owner_stream_next", None)
                if hook is None:
                    return ("gone", "driver stream owner gone")
                return hook(task_id, index, timeout)
            return self._stream_sub_via_peer(owner, owner[1], task_id,
                                             index, timeout)
        if kind == "w":
            node_hex, wid = owner[1], owner[2]
            if node_hex == self.hex:
                return self._stream_sub_local(wid, task_id, index, timeout)
            if in_process:
                peer = head.nodes.get(node_hex)
                if peer is not None and hasattr(peer, "store"):
                    # in-process peer node: ask its worker directly
                    return peer._stream_sub_local(wid, task_id, index,
                                                  timeout)
            return self._stream_sub_via_peer(owner, node_hex, task_id,
                                             index, timeout)
        return ("gone", "unroutable stream owner")

    def serve_stream_sub_local(self, owner, task_id, index, timeout):
        """Peer-facing entry: serve a subscription whose owner lives in
        THIS process (the terminal hop of a psub)."""
        kind = owner[0] if owner else None
        if kind == "d" and hasattr(self.head, "nodes"):
            hook = getattr(self.head, "owner_stream_next", None)
            if hook is None:
                return ("gone", "driver stream owner gone")
            return hook(task_id, index, timeout)
        if kind == "w" and owner[1] == self.hex:
            return self._stream_sub_local(owner[2], task_id, index, timeout)
        return ("gone", "stream owner not on this node")

    def _stream_sub_local(self, worker_id, task_id, index, timeout):
        """Round-trip to the owner worker on THIS node over its channel."""
        if isinstance(worker_id, bytes):
            worker_id = WorkerID(worker_id)  # routes carry raw id bytes
        from .exceptions import format_death_cause

        with self._lock:
            w = self._workers.get(worker_id)
        if w is None or w.state == "dead":
            return ("gone", format_death_cause("stream owner worker died",
                                               self.hex))
        req_id, slot = self._ssub_slot(worker_id)
        try:
            w.channel.send("ssub", req_id, task_id, index, timeout)
        except OSError:
            self._ssub_reply(req_id, None)
            return ("gone", format_death_cause("stream owner worker died",
                                               self.hex, w.pid))
        if not slot[0].wait((timeout or 0) + 5.0):
            with self._ssub_lock:
                self._ssub_pending.pop(req_id, None)
            return ("wait",)
        return slot[1]

    def _stream_sub_via_peer(self, owner, target_hex, task_id, index,
                             timeout):
        """Forward the subscription one hop to the owner's node."""
        handle = self._peer_handle_for(target_hex)
        if handle is None:
            return ("gone", "stream owner node gone")
        if not isinstance(handle, (tuple, list)):
            return handle.serve_stream_sub_local(owner, task_id, index,
                                                 timeout)
        ch = self._peer_channel(target_hex, tuple(handle))
        if ch is None:
            return ("gone", "stream owner node unreachable")
        req_id, slot = self._ssub_slot()
        try:
            ch.send("psub", req_id, owner, task_id, index, timeout)
        except (OSError, EOFError):
            self._ssub_reply(req_id, None)
            self._drop_peer(target_hex)
            return ("gone", "stream owner node unreachable")
        if not slot[0].wait((timeout or 0) + 5.0):
            with self._ssub_lock:
                self._ssub_pending.pop(req_id, None)
            return ("wait",)
        rep = slot[1]
        return rep if rep is not None else (
            "gone", "stream owner node unreachable")

    def _serve_peer_stream_sub(self, ch: Channel, req_id, owner, task_id,
                               index, timeout) -> None:
        """Server side of a peer 'psub'. Driver-owned streams probe
        inline first (steady state: the item is already in the owner
        table — no thread spawn per item); worker-owned streams and
        parking rounds go off-thread (the ssub round-trip / wait must
        not block the peer reader)."""
        hook = getattr(self.head, "owner_stream_next", None)
        if (owner and owner[0] == "d" and hook is not None
                and hasattr(self.head, "nodes")):
            try:
                rep = hook(task_id, index, 0)
            except Exception:
                rep = None
            if rep is not None and rep[0] != "wait":
                try:
                    ch.send("psubrep", req_id, rep)
                except (OSError, EOFError):
                    pass
                return

        def run():
            try:
                rep = self.serve_stream_sub_local(owner, task_id, index,
                                                  timeout)
            except Exception:
                rep = ("gone", "stream owner errored")
            try:
                ch.send("psubrep", req_id, rep)
            except (OSError, EOFError):
                pass  # subscriber's node gone

        threading.Thread(target=run, daemon=True,
                         name=f"psub-{self.hex[:6]}").start()

    # ---- spillback -------------------------------------------------------

    def _maybe_spill(self, spec: TaskSpec, origin: tuple) -> bool:
        cfg = global_config()
        with self._lock:
            depth = len(self._local_queue)
        if depth <= cfg.direct_spill_queue_factor * self.max_workers:
            return False
        cands = self._peer_candidates()
        if not cands:
            return False
        with self._peer_lock:
            now = time.monotonic()
            fresh = {h: q for h, (v, q, ts) in self._peer_loads.items()
                     if now - ts < 2.0}
            cands = [(h, handle,
                      fresh.get(h, q) + self._peer_inflight.get(h, 0))
                     for h, handle, q in cands]
        cands.sort(key=lambda c: c[2])
        peer_hex, handle, queue = cands[0]
        if queue >= depth:
            return False  # everyone is as busy as we are
        if not isinstance(handle, (tuple, list)):
            # in-process peer Node: direct call, reply hops back through us.
            # Tracked in _forwarded (peer stored as the Node object) so
            # cancel_direct can reach the peer's queue/worker.
            spec.direct_hops += 1
            with self._lock:
                self._forwarded[spec.task_id] = (origin, spec, handle)
                self._lease_args_locked(spec)
            handle.submit_direct(spec, ("node", self, origin))
            self._emit_spillback(spec, handle.hex, depth)
            return True
        ch = self._peer_channel(peer_hex, handle)
        if ch is None:
            return False
        # Stamp the hop only once delivery is committed — a failed spill
        # must leave the task eligible for later stealing/rebalancing.
        spec.direct_hops += 1
        with self._lock:
            self._forwarded[spec.task_id] = (origin, spec, peer_hex)
            self._lease_args_locked(spec)
        with self._peer_lock:
            self._peer_inflight[peer_hex] = \
                self._peer_inflight.get(peer_hex, 0) + 1
        try:
            ch.send("psubmit", pickle.dumps(spec))
        except (OSError, EOFError):
            spec.direct_hops -= 1
            with self._lock:
                self._forwarded.pop(spec.task_id, None)
            self._task_departed(spec.task_id)
            self._drop_peer(peer_hex)
            return False
        self._emit_spillback(spec, peer_hex, depth)
        return True

    def _emit_spillback(self, spec, peer_hex: str, depth: int) -> None:
        """Cluster event for a direct-task spillback, rate-limited to one
        per peer per second (spill waves are bursty)."""
        now = time.monotonic()
        last = getattr(self, "_spill_event_last", None)
        if last is None:
            last = self._spill_event_last = {}
        if now - last.get(peer_hex, 0.0) < 1.0:
            return
        last[peer_hex] = now
        from ray_tpu.util import events as events_mod

        events_mod.emit(
            "INFO", events_mod.SOURCE_SCHEDULER,
            f"spillback: node {self.hex[:8]} (queue depth {depth}) "
            f"forwarded {spec.function_name} to peer {peer_hex[:8]}",
            entity_id=self.hex, peer=peer_hex, queue_depth=depth,
            function=spec.function_name)

    def _peer_candidates(self) -> List[tuple]:
        """[(hex, Node | addr, queue_depth)] of alive CPU peers."""
        head = self.head
        out: List[tuple] = []
        view = getattr(head, "cluster_view", None)
        if view is not None:  # daemon side (RemoteHead)
            for e in view:
                if (e.get("hex") != self.hex and e.get("alive")
                        and e.get("addr")
                        and e.get("resources", {}).get("CPU", 0) > 0):
                    out.append((e["hex"], tuple(e["addr"]),
                                e.get("queue", 0)))
            return out
        # in-process side: peers straight off the head's node table
        with head._lock:
            items = list(head.nodes.items())
        for h, n in items:
            if h == self.hex or not getattr(n, "alive", False):
                continue
            if hasattr(n, "store"):  # local Node
                if n.resources.total.get("CPU") > 0:
                    out.append((h, n, len(n._local_queue)))
            else:  # NodeProxy: reach the daemon via its object server
                load = head.node_loads.get(h, {})
                if n.resources_total.get("CPU", 0) > 0:
                    out.append((h, tuple(n.object_addr),
                                load.get("queue_depth", 0)))
        return out

    def _peer_channel(self, peer_hex: str, addr) -> Optional[Channel]:
        with self._peer_lock:
            ch = self._peers.get(peer_hex)
            if ch is not None:
                return ch
        key = self._peer_key or getattr(self.head, "cluster_key", None) \
            or getattr(self.head, "_cluster_key", None)
        if key is None:
            return None
        import multiprocessing.connection as mpc
        import socket

        try:
            # mpc.Client has no connect timeout (~2 min OS default on a
            # partitioned host, which would stall the submitter's reader
            # loop): probe reachability with a bounded connect first
            socket.create_connection(tuple(addr), timeout=2.0).close()
            conn = mpc.Client(address=tuple(addr), family="AF_INET",
                              authkey=key)
            from .protocol import set_nodelay

            set_nodelay(conn)
            conn.send(("peer_hello", self.hex))
            ch = Channel(conn)
        except Exception:
            return None
        with self._peer_lock:
            cur = self._peers.get(peer_hex)
            if cur is not None:
                ch.close()
                return cur
            self._peers[peer_hex] = ch
        threading.Thread(target=self._peer_reader, args=(peer_hex, ch),
                         daemon=True, name=f"peer-{peer_hex[:6]}").start()
        return ch

    def _peer_reader(self, peer_hex: str, ch: Channel) -> None:
        while True:
            try:
                tag, payload = ch.recv()
            except (EOFError, OSError, TypeError):
                break
            if tag == "pload":
                self.on_peer_load(*payload)
                continue
            if tag == "pstolen":
                # work we asked to steal: execute here, reply over ch
                try:
                    spec = pickle.loads(payload[0])
                except Exception:
                    continue
                self.submit_direct(spec, ("peer", ch))
                continue
            if tag == "pstream":
                self.on_peer_stream_item(*payload)
                continue
            if tag == "psub":
                # stream subscription for an owner living in this process
                self._serve_peer_stream_sub(ch, *payload)
                continue
            if tag == "psubrep":
                self._ssub_reply(*payload)
                continue
            if tag == "pdone":
                try:
                    task_id, err_name, results, exec_hex = payload
                except ValueError:
                    break  # malformed/mixed-version peer: drop it
                with self._lock:
                    entry = self._forwarded.pop(task_id, None)
                self._task_departed(task_id)
                with self._peer_lock:
                    n = self._peer_inflight.get(peer_hex, 0)
                    if n > 0:
                        self._peer_inflight[peer_hex] = n - 1
                if entry is not None:
                    self._reply_direct(entry[0], task_id, err_name, results,
                                       exec_hex)
        self._drop_peer(peer_hex)

    def _drop_peer(self, peer_hex: str) -> None:
        """Peer channel died: fail its forwarded tasks (owners retry)."""
        with self._peer_lock:
            ch = self._peers.pop(peer_hex, None)
            self._peer_inflight.pop(peer_hex, None)
        if ch is not None:
            ch.close()
        with self._lock:
            lost = [(tid, e) for tid, e in self._forwarded.items()
                    if e[2] == peer_hex]
            for tid, _ in lost:
                self._forwarded.pop(tid, None)
        for tid, (origin, spec, _) in lost:
            self._task_departed(tid)
            self._reply_direct(origin, tid, "NodeDiedError", [])

    # ---- batched head events --------------------------------------------

    def _append_devent(self, spec: TaskSpec, err_name, sealed_oids,
                       t_start: Optional[float] = None) -> None:
        cfg = global_config()
        ev = (spec.task_id.binary(), spec.function_name, err_name,
              sealed_oids, t_start or time.time(), time.time())
        if hasattr(self.head, "nodes"):
            # in-process node: the head is a method call away — publish
            # synchronously so state API / timeline / waiters see the task
            # immediately (batching only pays off across a daemon link)
            self._publish_devents([ev])
            return
        flush = None
        with self._dev_lock:
            if not self._devents:
                self._dev_first = time.monotonic()
            self._devents.append(ev)
            if len(self._devents) >= cfg.direct_event_batch_size:
                flush, self._devents = self._devents, []
        if flush:
            self._publish_devents(flush)

    def _publish_devents(self, batch) -> None:
        try:
            self.head.publish_direct_events(self.hex, batch)
        except Exception:
            pass  # head link lost: daemon is shutting down

    def _ensure_direct_flusher(self) -> None:
        if hasattr(self.head, "nodes"):
            return  # in-process node: events publish synchronously
        with self._dev_lock:
            if self._dev_flusher_started:
                return
            self._dev_flusher_started = True
        cfg = global_config()
        interval = max(0.005, cfg.direct_event_flush_ms / 1000.0)

        def loop():
            while self.alive:
                time.sleep(interval)
                flush = None
                with self._dev_lock:
                    if self._devents and (time.monotonic() - self._dev_first
                                          >= interval):
                        flush, self._devents = self._devents, []
                if flush:
                    self._publish_devents(flush)

        threading.Thread(target=loop, daemon=True,
                         name=f"devents-{self.hex[:6]}").start()

    def _pump(self) -> None:
        """Match queued tasks with idle workers; start workers as needed.

        When no worker is idle, plain unbound tasks are staged onto a busy
        plain-task worker up to ``worker_pipeline_depth`` deep (reference:
        normal_task_submitter lease pipelining) so the worker starts the
        next task without waiting out the done->dispatch round trip.
        """
        cfg = global_config()
        depth = max(1, cfg.worker_pipeline_depth)
        direct_cap = max(1, int(self.max_workers * cfg.direct_slot_fraction))
        to_send: List[Tuple[WorkerHandle, TaskSpec, dict]] = []
        with self._lock:
            # one scan per pump (not per task): assignments made in this
            # call adjust the cached count below
            direct_running = self._direct_running_locked()
            while self._local_queue:
                idx = 0
                spec, binding = self._local_queue[0]
                if (spec.task_id in self._direct
                        and direct_running >= direct_cap):
                    # direct tasks at their slot cap: let a waiting
                    # head-dispatched (resource-bound) task leapfrog so the
                    # scheduler's placements aren't starved by a direct
                    # flood (priority-inversion guard). With no head task
                    # waiting the cap does not apply (work conservation).
                    for j in range(1, len(self._local_queue)):
                        s2, b2 = self._local_queue[j]
                        if s2.task_id not in self._direct:
                            idx, spec, binding = j, s2, b2
                            break
                w = None
                while self._idle:
                    cand = self._idle.popleft()
                    if cand.state == "idle":
                        w = cand
                        break
                if w is None:
                    # Prefer starting a new worker while under the limit —
                    # staging must never strand a task behind a long task
                    # when free capacity exists. Queued actor creations
                    # each get a dedicated worker beyond the pool.
                    active = sum(1 for x in self._workers.values()
                                 if x.state in ("idle", "busy")) + self._num_starting
                    limit = self.max_workers + sum(
                        1 for s, _ in self._local_queue if s.is_actor_creation)
                    if active < limit:
                        self._start_worker_locked()
                        break
                    # at capacity: stage onto a busy plain-task worker
                    if not spec.is_actor_creation and not binding:
                        for cand in self._workers.values():
                            if (cand.state == "busy"
                                    and len(cand.assigned) < depth
                                    and all(not s.is_actor_creation and not b
                                            for s, b, _ in
                                            cand.assigned.values())):
                                w = cand
                                break
                    if w is None:
                        break
                del self._local_queue[idx]
                if spec.task_id in self._direct:
                    direct_running += 1
                w.state = "busy"
                # stamp the attempt at assignment: spec objects are shared
                # with the head and mutate on retry, so a late finish must
                # carry the attempt it actually ran
                w.assigned[spec.task_id] = (spec, binding, spec.attempt)
                to_send.append((w, spec, binding))
            # rescue: a worker sits idle with nothing queued while another
            # has staged-unstarted tasks — ask for one back so it isn't
            # stuck behind a long/blocked task. (Not triggered by workers
            # merely starting, and never for tasks staged in this call —
            # both would ping-pong stage/unstage.)
            unstage: List[Tuple[WorkerHandle, object]] = []
            just_staged = {spec.task_id for _, spec, _ in to_send}
            if not self._local_queue and self._idle:
                for cand in self._workers.values():
                    if cand.state == "busy" and len(cand.assigned) > 1:
                        last_tid = next(reversed(cand.assigned))
                        if last_tid not in just_staged:
                            unstage.append((cand, last_tid))
            # refill the prewarmed pool: assignments above may have just
            # consumed idle workers (a serve scale-out claims one warm
            # process per new replica) — fork replacements NOW so the
            # next ramp step finds the pool full again
            self._ensure_prewarm_locked()
        for w, spec, binding in to_send:
            try:
                w.channel.send("exec", pickle.dumps(spec), binding)
            except OSError:
                self._on_worker_dead(w)
        for w, tid in unstage:
            try:
                w.channel.send("unstage", tid)
            except OSError:
                self._on_worker_dead(w)
        if not to_send and not unstage:
            # nothing to do locally: try pulling work from a loaded peer
            self._maybe_steal()

    # ---- work stealing ---------------------------------------------------
    # (round 4, audit weak #7: spillback was submit-time-only — a task
    # queued behind a long task was never re-balanced. Idle nodes now PULL
    # queued direct tasks from the deepest-queued peer over the same mesh
    # the spill push uses; reference analog: LocalTaskManager spillback
    # re-evaluation, inverted into a thief-initiated protocol.)

    def _steal_ticker(self) -> None:
        while not self._stop_event.wait(0.5):
            try:
                self._gossip_load()
                self._maybe_steal()
            except Exception:
                pass

    def _gossip_load(self) -> None:
        """Push this node's queue depth to every established peer
        channel (one-way). Only connected peers hear it — exactly the
        nodes actively exchanging work, where freshness matters."""
        with self._peer_lock:
            chans = list(self._peers.items())
        if not chans:
            return
        self._gossip_version += 1
        with self._lock:
            depth = len(self._local_queue)
        for peer_hex, ch in chans:
            try:
                ch.send("pload", self.hex, depth, self._gossip_version)
            except (OSError, EOFError):
                pass  # peer death handled by its reader

    def on_peer_load(self, peer_hex: str, depth: int,
                     version: int) -> None:
        with self._peer_lock:
            cur = self._peer_loads.get(peer_hex)
            if cur is None or version >= cur[0]:
                self._peer_loads[peer_hex] = (version, depth,
                                              time.monotonic())

    def _maybe_steal(self) -> None:
        cfg = global_config()
        if not cfg.direct_steal_enabled:
            return
        now = time.monotonic()
        if now - getattr(self, "_last_steal", 0.0) < \
                cfg.direct_steal_interval_ms / 1000.0:
            return
        self._last_steal = now
        with self._lock:
            if self._local_queue or not self._idle:
                return
            free = sum(1 for w in self._workers.values()
                       if w.state == "idle")
        cands = self._peer_candidates()
        if not cands:
            return
        with self._peer_lock:
            now = time.monotonic()
            fresh = {h: q for h, (v, q, ts) in self._peer_loads.items()
                     if now - ts < 2.0}
        cands = [(h, handle, fresh.get(h, q)) for h, handle, q in cands]
        cands.sort(key=lambda c: -c[2])
        peer_hex, handle, queue = cands[0]
        if queue < cfg.direct_steal_min_queue:
            return
        want = max(1, min(free, queue // 2))
        if not isinstance(handle, (tuple, list)):
            # in-process peer: pop eligible tasks directly
            for spec, origin in handle._pop_stealable(want):
                with handle._lock:
                    handle._forwarded[spec.task_id] = (origin, spec, self)
                self.submit_direct(spec, ("node", handle, origin))
            return
        ch = self._peer_channel(peer_hex, handle)
        if ch is None:
            return
        try:
            ch.send("psteal", want)
        except (OSError, EOFError):
            self._drop_peer(peer_hex)

    def _pop_stealable(self, k: int):
        """Victim side: hand over up to k queued, unstarted direct plain
        tasks (skip actor creations, resource-bound, already-hopped-out
        tasks). Returns [(spec, origin)] with the _direct entries removed
        — the caller forwards them and owns the reply routing."""
        out = []
        with self._lock:
            keep = deque()
            while self._local_queue and len(out) < k:
                spec, binding = self._local_queue.pop()  # steal the TAIL
                entry = self._direct.get(spec.task_id)
                if (entry is None or binding or spec.is_actor_creation
                        or spec.actor_id is not None
                        or spec.direct_hops >= 2):
                    keep.appendleft((spec, binding))
                    continue
                # NOTE: the arg lease (_leased_tasks) intentionally stays:
                # every caller immediately re-tracks the task in
                # _forwarded (reply still routes through this victim), so
                # the lease releases on the normal pdone/depart path
                del self._direct[spec.task_id]
                spec.direct_hops += 1
                out.append((spec, entry[0]))
            self._local_queue.extend(keep)
        return out

    def _serve_steal(self, ch: Channel, k: int) -> None:
        """Victim side of a remote steal: ship tasks; replies come back
        over the same channel ('pdone' handled by _serve_peer)."""
        marker = ("_stolen", ch)
        stolen = self._pop_stealable(int(k))
        for i, (spec, origin) in enumerate(stolen):
            with self._lock:
                self._forwarded[spec.task_id] = (origin, spec, marker)
            try:
                ch.send("pstolen", pickle.dumps(spec))
            except (OSError, EOFError):
                # thief gone: run the rest ourselves (every popped task
                # must land somewhere — a dropped one hangs its owner)
                for spec2, origin2 in stolen[i:]:
                    with self._lock:
                        self._forwarded.pop(spec2.task_id, None)
                        spec2.direct_hops -= 1
                        self._direct[spec2.task_id] = (origin2, spec2,
                                                       time.time())
                    self.dispatch(spec2, {})
                return

    def on_peer_session_closed(self, ch) -> None:
        """A peer session (thief) died: fail its in-flight stolen tasks
        back to their owners (they retry per max_retries)."""
        marker = ("_stolen", ch)
        with self._lock:
            lost = [(tid, e) for tid, e in self._forwarded.items()
                    if e[2] == marker]
            for tid, _e in lost:
                self._forwarded.pop(tid, None)
        for tid, (origin, spec, _m) in lost:
            self._task_departed(tid)
            self._reply_direct(origin, tid, "NodeDiedError", [])

    def on_peer_done(self, task_id, err_name, results, exec_hex) -> None:
        """A completion for a task we handed to a peer (stolen or
        spilled) arriving over either peer-session direction."""
        with self._lock:
            entry = self._forwarded.pop(task_id, None)
        self._task_departed(task_id)
        if entry is not None:
            self._reply_direct(entry[0], task_id, err_name, results,
                               exec_hex)

    def on_peer_stream_item(self, task_id, index: int,
                            data: Optional[bytes], exec_hex) -> None:
        """A stream-item announcement for a task we handed to a peer:
        pass it along toward the owner (the forwarding entry stays — the
        completion is still to come, FIFO behind the items)."""
        with self._lock:
            entry = self._forwarded.get(task_id)
        if entry is not None:
            self._reply_stream_item(entry[0], task_id, index, data,
                                    exec_hex)

    def _direct_running_locked(self) -> int:
        """Worker slots currently held by direct (head-bypass) tasks."""
        n = 0
        for w in self._workers.values():
            for s, _, _ in w.assigned.values():
                if s.task_id in self._direct:
                    n += 1
        return n

    # ------------------------------------------------------------ workers

    def _ensure_prewarm_locked(self) -> None:
        """Keep ``serve_prewarm_pool_size`` idle (or starting) workers on
        standby beyond current demand, so a scale-out consumes a warm
        pre-forked process instead of paying the fork+import cold start
        on the ramp step (the scale-out p99 tail killer). Bounded: never
        pushes total workers past max_workers + pool size."""
        target = global_config().serve_prewarm_pool_size
        if target <= 0 or not self.alive:
            return
        warm = sum(1 for w in self._idle if w.state == "idle") \
            + self._num_starting
        active = sum(1 for x in self._workers.values()
                     if x.state in ("idle", "busy")) + self._num_starting
        cap = self.max_workers + target
        while warm < target and active < cap:
            self._start_worker_locked()
            warm += 1
            active += 1

    def _start_worker_locked(self) -> None:
        self._num_starting += 1
        env = dict(os.environ)
        env["RAY_TPU_NODE_HEX"] = self.hex
        if self.resources.total.get("TPU") == 0:
            # CPU-only node: skip the TPU plugin registration in sitecustomize
            # (it imports jax, ~2s per process start)
            env.pop("PALLAS_AXON_POOL_IPS", None)
        # make ray_tpu importable in the worker regardless of driver cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        log_path = os.path.join(self.session_dir, "logs")
        os.makedirs(log_path, exist_ok=True)
        log_file = os.path.join(log_path, f"worker-{time.time_ns()}.log")
        out = open(log_file, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_runtime",
             "--address", self._sock_path, "--authkey", self._authkey.hex()],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            cwd=os.getcwd(),
        )
        self._starting_pids.add(proc.pid)
        self._tail_files[log_file] = [0, proc.pid, None]
        self._ensure_log_tailer()
        # handle registered on accept
        threading.Thread(
            target=self._reap, args=(proc,), daemon=True
        ).start()

    def _reap(self, proc: subprocess.Popen) -> None:
        proc.wait()
        # a worker that died before registering would leak _num_starting
        # (and with it a phantom slot in _pump's active count) forever
        died_starting = False
        with self._lock:
            if proc.pid in self._starting_pids:
                self._starting_pids.discard(proc.pid)
                self._num_starting = max(0, self._num_starting - 1)
                died_starting = True
            for st in self._tail_files.values():
                if st[1] == proc.pid and st[2] is None:
                    st[2] = time.monotonic()  # tailer drops it after a
                    # final read window
        if died_starting and self.alive:
            # the freed capacity must re-pump NOW: with all tasks already
            # queued, no future event would ever start a replacement
            # worker and the queue would strand forever
            self._pump()

    def _accept_loop(self) -> None:
        import multiprocessing.context as _mpctx

        while self.alive:
            try:
                conn = self._listener.accept()
            except _mpctx.AuthenticationError:
                # worker killed mid-handshake (node/cluster shutdown race)
                continue
            except (OSError, EOFError):
                return
            # handshake off-thread: a slow registrant must not hold up
            # accept() (concurrent prestarts would pile into the backlog)
            threading.Thread(target=self._register_worker, args=(conn,),
                             daemon=True,
                             name=f"register-{self.hex[:6]}").start()

    def _register_worker(self, conn) -> None:
        channel = Channel(conn)
        try:
            tag, (pid,) = channel.recv()
            assert tag == "register"
        except Exception:
            channel.close()
            return
        self._finish_register(channel, pid)

    def _finish_register(self, channel, pid) -> None:
        wid = WorkerID.from_random()
        w = WorkerHandle(worker_id=wid, channel=channel, pid=pid, state="idle")
        with self._lock:
            if pid in self._starting_pids:
                self._starting_pids.discard(pid)
                self._num_starting = max(0, self._num_starting - 1)
            self._workers[wid] = w
            self._idle.append(w)
        init_info = {
            "worker_id": wid.binary(),
            "node_hex": self.hex,
            "node_ip": self.node_ip,
            "job_id": self.head.job_id.binary(),
            "arena_path": self.store.arena_path,
            "arena_capacity": self.store.capacity,
            "session_dir": self.session_dir,
            "config": global_config().to_json(),
            # driver-visible import roots: functions pickled BY REFERENCE
            # against modules the driver loaded from script-local dirs
            # (pytest rootdir inserts, sys.path hacks) must resolve in the
            # worker too (reference: ray ships the driver's sys.path via
            # the runtime env's working_dir/py_modules mechanism)
            "sys_path": [p for p in sys.path if p],
        }
        channel.send("init", init_info)
        w.reader = threading.Thread(
            target=self._reader_loop, args=(w,), daemon=True,
            name=f"reader-{wid.hex()[:6]}",
        )
        w.reader.start()
        self._pump()

    def _reader_loop(self, w: WorkerHandle) -> None:
        try:
            self._reader_loop_inner(w)
        except Exception:
            # a message-processing bug must NEVER silently kill this
            # thread: the worker's done/rpc messages would go unread and
            # its tasks hang forever. Log loudly and declare the worker
            # dead so its work is retried.
            import traceback

            print(f"[ray_tpu] node {self.hex[:6]} worker-reader crashed:\n"
                  + traceback.format_exc(), file=sys.stderr, flush=True)
            self._on_worker_dead(w)

    def _reader_loop_inner(self, w: WorkerHandle) -> None:
        while True:
            try:
                tag, payload = w.channel.recv()
            except (EOFError, OSError):
                self._on_worker_dead(w)
                return
            if tag == "done":
                task_id, results, err_name = payload
                self._on_task_done(w, task_id, results, err_name)
            elif tag == "store":
                req_id, op, *args = payload
                if op in ("get", "wait", "create"):
                    self._handler_pool.submit(self._handle_store, w, req_id, op, args)
                else:
                    self._handle_store(w, req_id, op, args)
            elif tag == "rpc":
                req_id, op, *args = payload
                if op in ("pub_poll", "stream_sub"):
                    # long-parking rounds (pubsub polls, stream
                    # subscriptions) get their own thread — they must not
                    # starve the bounded shared pool
                    threading.Thread(
                        target=self._handle_rpc, args=(w, req_id, op, args),
                        daemon=True, name="pub-poll").start()
                else:
                    self._handler_pool.submit(self._handle_rpc, w, req_id,
                                              op, args)
            elif tag == "pub1":
                # one-way fire-and-forget publish (tracing hot path)
                try:
                    self.head.publish_oneway(payload[0], payload[1])
                except Exception:
                    pass
            elif tag == "dsubmit":
                # direct (head-bypass) submission from this worker
                spec = pickle.loads(payload[0])
                self.submit_direct(spec, ("worker", w.worker_id))
            elif tag == "dcancel":
                self.cancel_direct(payload[0], payload[1])
            elif tag == "srep":
                # owner worker's reply to a stream_sub round ("ssub")
                self._ssub_reply(*payload)
            elif tag == "stream":
                task_id, index, data = payload
                self._on_worker_stream_item(task_id, index, data)
            elif tag == "metrics":
                self.head.on_worker_metrics(
                    f"{self.hex[:6]}:{w.pid}", payload[0])
            elif tag == "spans":
                # worker flight-recorder batch -> head span store; the
                # node stamps source AND its node hex (the head keys
                # clock offsets by node)
                try:
                    self.head.on_worker_spans(
                        f"{self.hex[:6]}:{w.pid}",
                        dict(payload[0], node_hex=self.hex))
                except Exception:
                    pass
            elif tag == "cevents":
                # worker cluster events -> head event ring (one-way)
                try:
                    self.head.record_cluster_events(payload[0])
                except Exception:
                    pass
            elif tag == "refs":
                # worker ref-table report -> head ownership table; the
                # node stamps the source id (same keying as metrics)
                try:
                    self.head.on_ref_report(f"{self.hex[:6]}:{w.pid}",
                                            payload[0])
                except Exception:
                    pass
            elif tag == "stack_rep":
                # worker's collapsed-stack reply to a "stack" round
                req_id, text = payload
                slot = self._stack_pending.get(req_id)
                if slot is not None:
                    slot[1] = text
                    slot[0].set()
            elif tag == "unstaged":
                # worker handed back a staged-unstarted task: requeue it
                tid = payload[0]
                with self._lock:
                    entry = w.assigned.pop(tid, None)
                    if entry is not None:
                        self._local_queue.appendleft(entry[:2])
                        if w.state == "busy" and not w.assigned:
                            w.state = "idle"
                            self._idle.append(w)
                if entry is not None:
                    self._pump()
            elif tag == "exit":
                # graceful actor exit
                self._on_worker_exit(w)
                return

    def _on_worker_stream_item(self, task_id, index: int,
                               data: Optional[bytes]) -> None:
        """A worker announced stream item ``index``. Direct tasks route it
        straight to the owner over the reply chain (zero head records);
        head-path tasks keep the head stream-record protocol. Inline
        payloads are also sealed locally so the object stays directory-
        resolvable for borrowers (location rides the completion devent on
        the direct path)."""
        oid = ObjectID.for_stream(task_id, index)
        with self._lock:
            entry = self._direct.get(task_id)
        if entry is not None:
            if data is not None:
                try:
                    self.store.put_inline(oid, data, False)
                    with self._lock:
                        self._direct_stream_oids.setdefault(
                            task_id, []).append(oid)
                except Exception:
                    pass  # store full: the owner's inline copy suffices
            self._reply_stream_item(entry[0], task_id, index, data,
                                    self.hex)
            return
        # head path: seal + register the location, then announce
        if data is not None:
            try:
                self.store.put_inline(oid, data, False)
                self.head.on_object_sealed(oid, self.hex)
            except Exception:
                pass
        self.head.on_stream_item(task_id, index)

    def _reply(self, w: WorkerHandle, req_id: int, ok: bool, value) -> None:
        try:
            w.channel.send("rep", req_id, ok, value)
        except OSError:
            pass

    def _handle_store(self, w: WorkerHandle, req_id: int, op: str, args) -> None:
        try:
            if op == "get":
                oid, timeout, *rest = args
                hint = rest[0] if rest else None
                rep = self.head.get_object_for_node(self, oid, timeout,
                                                    hint=hint)
                self._reply(w, req_id, True, rep)
            elif op == "wait":
                oids, num_returns, timeout, *rest = args
                fetch_local = rest[0] if rest else False
                ready = self.head.wait_objects(oids, num_returns, timeout,
                                               fetch_local)
                self._reply(w, req_id, True, ready)
            elif op == "create":
                oid, size = args
                offset, _ = self.store.create(oid, size)
                self._reply(w, req_id, True, offset)
            elif op == "seal":
                oid, is_error = args
                self.store.seal(oid, is_error)
                self.head.on_object_sealed(oid, self.hex)
                self._reply(w, req_id, True, None)
            elif op == "put_inline":
                oid, data, is_error = args
                self.store.put_inline(oid, data, is_error)
                self.head.on_object_sealed(oid, self.hex)
                self._reply(w, req_id, True, None)
            else:
                self._reply(w, req_id, False, ValueError(f"bad store op {op}"))
        except Exception as e:  # noqa: BLE001
            self._reply(w, req_id, False, e)

    def _handle_rpc(self, w: WorkerHandle, req_id: int, op: str, args) -> None:
        try:
            if op == "stream_sub":
                # owner-routed stream subscription: served by this node's
                # routing (worker/peer/driver channels) — the head never
                # sees it
                result = self.serve_stream_sub(*args)
            else:
                result = self.head.handle_worker_rpc(self, w, op, args)
            self._reply(w, req_id, True, result)
        except Exception as e:  # noqa: BLE001
            self._reply(w, req_id, False, e)

    # ------------------------------------------------------------ lifecycle

    def _on_task_done(self, w: WorkerHandle, task_id, results, err_name) -> None:
        with self._lock:
            entry = w.assigned.pop(task_id, None)
            direct = self._direct.pop(task_id, None)
            if entry is not None:
                spec, binding, attempt = entry
                if spec.is_actor_creation and err_name is None:
                    w.state = "actor"
                    w.actor_id = spec.actor_id
                    # direct actor-call routing table (set BEFORE the head
                    # learns ALIVE, so owners resolving via the head never
                    # race ahead of this index)
                    self._actor_workers[spec.actor_id] = w.worker_id
                elif w.state == "busy" and not w.assigned:
                    w.state = "idle"
                    self._idle.append(w)
            else:
                # actor task done (worker stays "actor") or stale
                spec, binding, attempt = None, None, None
        if direct is not None:
            # head-bypass path: owner settles (retries live there)
            self._finish_direct(direct[0], direct[1], task_id, results,
                                err_name, t_start=direct[2])
            self._task_departed(task_id)
        else:
            # The head decides whether to seal results (it may retry).
            self.head.on_task_finished(self, task_id, err_name, spec, binding,
                                       results, worker_id=w.worker_id,
                                       attempt=attempt)
        self._pump()

    def _on_worker_exit(self, w: WorkerHandle) -> None:
        with self._lock:
            w.state = "dead"
            self._workers.pop(w.worker_id, None)
            lost = self._drop_actor_direct_locked(w)
        self._fail_worker_ssubs(w.worker_id, w.pid)
        self._fail_worker_stack_waiters(w.worker_id)
        # head first (same reasoning as _on_worker_dead): owners failing
        # these calls read the FSM for the attributed death cause
        self.head.on_worker_exit(self, w)
        for origin, spec, err in lost:
            self._task_departed(spec.task_id)
            self._reply_direct(origin, spec.task_id, err, [])

    def _drop_actor_direct_locked(self, w: WorkerHandle):
        """Remove a dead actor worker from the routing index and collect
        its in-flight direct calls as (origin, spec, err_name).

        Every ``_direct`` actor entry was already channel-sent to the
        worker process (``_submit_direct_actor`` dispatches immediately),
        so any of them MAY have executed: at-most-once demands
        ActorDiedError (retries consume max_task_retries). The
        provably-undelivered case — dispatch_to_worker failing — bounces
        ActorMissingError at submit time instead (never-executed ->
        always safe to resubmit, direct.py protocol)."""
        if w.actor_id is None:
            return []
        if self._actor_workers.get(w.actor_id) == w.worker_id:
            del self._actor_workers[w.actor_id]
        lost = []
        for tid, (origin, spec, _t0) in list(self._direct.items()):
            if spec.actor_id == w.actor_id:
                del self._direct[tid]
                self._direct_stream_oids.pop(tid, None)
                lost.append((origin, spec, "ActorDiedError"))
        return lost

    def _on_worker_dead(self, w: WorkerHandle) -> None:
        with self._lock:
            if w.state == "dead":
                return
            prev_state = w.state
            w.state = "dead"
            self._workers.pop(w.worker_id, None)
            assigned = list(w.assigned.values())
            w.assigned.clear()
            direct = [self._direct.pop(s.task_id)
                      for s, _, _ in assigned
                      if s.task_id in self._direct]
            direct_ids = {spec.task_id for _, spec, _ in direct}
            for tid in direct_ids:
                self._direct_stream_oids.pop(tid, None)
            lost_actor = self._drop_actor_direct_locked(w)
        w.channel.close()
        self._fail_worker_ssubs(w.worker_id, w.pid)
        self._fail_worker_stack_waiters(w.worker_id)
        head_assigned = [e for e in assigned if e[0].task_id not in direct_ids]
        # head FIRST, owner replies second: the owner's failure handling
        # (possibly inline on THIS thread for an in-process driver)
        # consults the actor FSM for the attributed death cause and the
        # restart decision — reporting the crash after the replies would
        # make it read a stale ALIVE
        if head_assigned:
            for spec, binding, _attempt in head_assigned:
                self.head.on_worker_crashed(self, w, spec, binding, prev_state)
        else:
            self.head.on_worker_crashed(self, w, None, None, prev_state)
        # direct tasks: the OWNER retries — report the crash straight back
        for origin, spec, _t0 in direct:
            self._task_departed(spec.task_id)
            self._reply_direct(origin, spec.task_id, "WorkerCrashedError", [])
        for origin, spec, err in lost_actor:
            self._task_departed(spec.task_id)
            self._reply_direct(origin, spec.task_id, err, [])
        self._pump()

    def cancel_task(self, task_id, worker_id: Optional[WorkerID],
                    force: bool) -> None:
        """Forward a cancel to the worker running ``task_id`` (or the given
        actor worker). Reference: CoreWorker::CancelTask -> executor interrupt."""
        with self._lock:
            target = None
            if worker_id is not None:
                target = self._workers.get(worker_id)
            else:
                for w in self._workers.values():
                    if task_id in w.assigned:
                        target = w
                        break
        if target is None:
            return
        try:
            target.channel.send("cancel", task_id)
        except OSError:
            pass
        if force:
            self.kill_worker(target.worker_id)

    def _ensure_log_tailer(self) -> None:
        """Tail worker log files -> head -> driver stderr (reference:
        log_monitor.py:581 tails per-proc files to the driver)."""
        if self._log_tailer_started or not global_config().log_to_driver:
            return
        self._log_tailer_started = True

        def tail():
            while self.alive:
                now = time.monotonic()
                for path, st in list(self._tail_files.items()):
                    try:
                        with open(path, "rb") as f:
                            f.seek(st[0])
                            data = f.read()
                    except OSError:
                        self._tail_files.pop(path, None)
                        continue
                    if data:
                        st[0] += len(data)
                        try:
                            self.head.on_worker_log(
                                self.hex, st[1],
                                data.decode("utf-8", "replace"))
                        except Exception:
                            pass
                    if st[2] is not None and now - st[2] > 2.0:
                        self._tail_files.pop(path, None)  # worker gone
                time.sleep(0.5)

        threading.Thread(target=tail, daemon=True,
                         name=f"logtail-{self.hex[:6]}").start()

    def push_object_to(self, oid, targets) -> int:
        """Broadcast-tree hop: deliver ``oid`` from this node's store to
        every (hex, addr) in ``targets`` (binomial fan-out)."""
        from .object_transfer import fan_out_push

        key = self._peer_key or getattr(self.head, "cluster_key", None) \
            or getattr(self.head, "_cluster_key", None)
        if key is None:
            return 0
        return fan_out_push(self.store, key, oid,
                            [t for t in targets if t[0] != self.hex])

    def update_node_ip(self, ip: str) -> None:
        """Upgrade this node's advertised IP and push it to every
        already-registered worker. Workers prestarted in __init__ received
        init_info with the loopback IP before start_node_server() learned
        the routable one; without this push an actor matched to such a
        worker would advertise 127.0.0.1 as its coordinator address in a
        multi-host Train bootstrap."""
        self.node_ip = ip
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            try:
                w.channel.send("node_ip", ip)
            except OSError:
                pass

    def start_object_server(self, authkey: bytes, host: Optional[str] = None):
        """Start the node-to-node chunk server (multi-host mode).

        Binds all interfaces when the node has a non-loopback ``node_ip``
        and advertises that IP, so cross-host pulls get a routable address.
        """
        from .object_transfer import ObjectServer

        if getattr(self, "object_server", None) is None:
            if host is None:
                host = ("127.0.0.1" if self.node_ip.startswith("127.")
                        else "0.0.0.0")
            self._peer_key = authkey
            self.object_server = ObjectServer(
                self.store, authkey, host,
                advertise_host=self.node_ip, node=self)
        return self.object_server

    def kill_worker(self, worker_id: WorkerID) -> None:
        with self._lock:
            w = self._workers.get(worker_id)
        if w is None:
            return
        try:
            w.channel.send("shutdown")
        except OSError:
            pass
        try:
            os.kill(w.pid, 9)
        except (OSError, ProcessLookupError):
            pass

    def num_workers(self) -> int:
        with self._lock:
            return len(self._workers)

    def collect_worker_stacks(self, duration_s: float,
                              timeout: float = 3.0) -> Dict[str, str]:
        """One bounded ``stack`` round per live worker: each samples its
        own threads for ``duration_s`` and replies one-way. Returns
        {"<node6>:<pid>": collapsed text}; dead/slow workers are simply
        absent (their pending slots are failed by _on_worker_dead)."""
        waiters = []
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            with self._lock:
                self._stack_seq += 1
                req_id = self._stack_seq
                slot = [threading.Event(), None, w.worker_id]
                self._stack_pending[req_id] = slot
            try:
                w.channel.send("stack", req_id,
                               int(duration_s * 1000))
            except OSError:
                self._stack_pending.pop(req_id, None)
                continue
            waiters.append((w, req_id, slot))
        out: Dict[str, str] = {}
        deadline = time.monotonic() + timeout + duration_s
        for w, req_id, slot in waiters:
            slot[0].wait(max(0.0, deadline - time.monotonic()))
            self._stack_pending.pop(req_id, None)
            if slot[1] is not None:
                out[f"{self.hex[:6]}:{w.pid}"] = slot[1]
        return out

    def _fail_worker_stack_waiters(self, worker_id) -> None:
        """Death path for the stack round: a dead worker's pending
        collectors wake now with no reply."""
        with self._lock:
            gone = [(rid, s) for rid, s in self._stack_pending.items()
                    if len(s) > 2 and s[2] == worker_id]
            for rid, _s in gone:
                self._stack_pending.pop(rid, None)
        for _rid, slot in gone:
            slot[0].set()

    def shutdown(self) -> None:
        self.alive = False
        self._stop_event.set()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            try:
                w.channel.send("shutdown")
            except OSError:
                pass
            try:
                os.kill(w.pid, 9)
            except (OSError, ProcessLookupError):
                pass
        from .protocol import close_listener

        close_listener(self._listener)  # wakes the parked accept()
        # reap the accept loop and the steal ticker so shutdown leaves
        # no threads behind
        self._accept_thread.join(timeout=2.0)
        if self._steal_thread is not None:
            self._steal_thread.join(timeout=2.0)
        if getattr(self, "object_server", None) is not None:
            self.object_server.close()
            # drop pooled transfer connections: this node's outbound conns
            # are dead weight now, and peers' conns to it will fail health
            # checks. Coarse (the pool is process-global; co-resident nodes
            # re-dial on their next pull) but leak-free.
            from .object_transfer import close_pool

            close_pool()
        self.store.close()
        self._handler_pool.shutdown(wait=False)
