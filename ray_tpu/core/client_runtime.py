"""Client-mode runtime: a remote driver proxying the API over TCP.

Reference: ``ray.init(address="ray://host:port")`` client mode
(python/ray/util/client/worker.py ``Worker`` — the client-side stub that
converts every public API call into an RPC). Same role here: this object
satisfies the runtime interface that ``ray_tpu.remote/get/put/wait`` and
the actor machinery call, but every operation crosses one authenticated
TCP channel to the head's ClientServer (core/client_server.py).

Serialization happens client-side (core/serialization.py), so values round
-trip exactly as in-process drivers'; TaskSpecs travel whole — the head
re-stamps the session's job id.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from . import serialization
from .ids import ObjectID, TaskID
from .protocol import Channel, RpcClient, connect, parse_address


class ClientRuntime:
    def __init__(self, address, cluster_key: bytes):
        if isinstance(address, str):
            address = parse_address(address)
        self._channel = connect(address, cluster_key)
        tag, payload = self._channel.recv()
        if tag != "welcome":
            raise ConnectionError(f"bad handshake from client server: {tag}")
        welcome = payload[0]
        from .protocol import check_protocol

        check_protocol(welcome)
        self.job_id = welcome["job_id"]
        self._node_id = welcome["node_id"]
        self._driver_task_id = welcome["driver_task_id"]
        self._rpc = RpcClient(self._channel)
        self._closed = False
        self._fn_cache = {}
        self._reader = threading.Thread(target=self._read_loop,
                                        name="client-rpc-reader", daemon=True)
        self._reader.start()

    # ---- plumbing ---------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while not self._closed:
                tag, payload = self._channel.recv()
                if tag == "reply":
                    self._rpc.handle_reply(*payload)
        except (EOFError, OSError, ConnectionError) as e:
            if not self._closed:
                self._rpc.fail_all(
                    ConnectionError(f"lost connection to head: {e!r}"))

    def _call(self, op: str, *args, timeout: Optional[float] = None):
        if self._closed:
            raise RuntimeError("client runtime is disconnected")
        return self._rpc.call("rpc", op, *args, timeout=timeout)

    def _notify(self, tag: str, *payload) -> None:
        if self._closed:
            return
        try:
            self._channel.send(tag, *payload)
        except Exception:
            pass

    def disconnect(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._notify("bye")
        try:
            self._channel.close()
        except Exception:
            pass
        # closing the channel pops the reader out of recv(); reap it so
        # disconnect() leaves no thread behind
        self._reader.join(timeout=2.0)

    # ---- runtime interface ------------------------------------------------
    @property
    def mode(self) -> str:
        return "CLIENT"

    def is_initialized(self) -> bool:
        return not self._closed

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    def put(self, value: Any, _owner=None):
        from .object_ref import ObjectRef

        sobj = serialization.serialize(value)
        buf = bytearray()
        sobj.write_into(buf)
        oid = self._call("put", bytes(buf))
        return ObjectRef(oid)

    def get(self, refs, timeout: Optional[float] = None) -> List[Any]:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        out = []
        for r in refs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            payload, is_error = self._call("get", r.id, remaining)
            value = serialization.deserialize(payload)
            if is_error:
                raise value
            out.append(value)
        return out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ready_ids = set(self._call(
            "wait", [r.id for r in refs], num_returns, timeout))
        ready = [r for r in refs if r.id in ready_ids]
        not_ready = [r for r in refs if r.id not in ready_ids]
        return ready, not_ready

    def submit_task(self, spec) -> list:
        from .object_ref import ObjectRef

        self._call("submit", spec)
        return [ObjectRef(oid) for oid in spec.return_ids()]

    def actor_method_call(self, spec) -> list:
        return self.submit_task(spec)

    def register_function(self, function_id: str, payload: bytes) -> None:
        self._call("register_function", function_id, payload)

    def get_function(self, function_id: str):
        import pickle

        if function_id not in self._fn_cache:
            payload = self._call("get_function", function_id)
            if payload is None:
                raise RuntimeError(f"function {function_id} not registered")
            self._fn_cache[function_id] = pickle.loads(payload)
        return self._fn_cache[function_id]

    def create_actor_record(self, spec, name, namespace, max_restarts,
                            detached, max_task_retries=0):
        self._call("create_actor", spec, name, namespace, max_restarts,
                   detached, max_task_retries)

    def get_actor_info(self, name: str, namespace: str):
        return self._call("get_actor_info", name, namespace)

    def kill_actor(self, actor_id, no_restart: bool = True):
        self._call("kill_actor", actor_id, no_restart)

    def cancel_task(self, oid, force: bool = False):
        self._call("cancel", oid, force)

    def kv(self, op: str, *args):
        return self._call("kv", op, args)

    def stream_next(self, task_id, index: int, timeout=None, owner=None):
        # the owner route rides along: the server (head process) resolves
        # owner-published streams via its node's stream_sub routing
        return self._call("stream_next", task_id, index, timeout, owner)

    def state_list(self, kind: str, limit: int = 1000):
        return self._call("state_list", kind, limit)

    # ---- refs (fire-and-forget over the ordered channel) ------------------
    def add_local_ref(self, oid: ObjectID) -> None:
        self._notify("refop", "add", oid)

    def remove_local_ref(self, oid: ObjectID) -> None:
        self._notify("refop", "del", oid)

    def add_borrow_ref(self, oid: ObjectID) -> None:
        self._notify("refop", "add", oid)

    # ---- cluster info -----------------------------------------------------
    def runtime_context(self) -> dict:
        return {
            "job_id": self.job_id,
            "node_id": self._node_id,
            "worker_id": b"client-driver",
            "task_id": self._driver_task_id,
            "actor_id": None,
            "accelerator_ids": {},
            "mode": "CLIENT",
        }

    def available_resources(self):
        return self._call("avail")

    def cluster_resources(self):
        return self._call("total")

    def nodes(self):
        return self._call("nodes")

    def create_placement_group(self, bundles, strategy, name=""):
        return self._call("create_pg", bundles, strategy, name)

    def placement_group_op(self, op: str, *args):
        return self._call("pg_op", op, args)
