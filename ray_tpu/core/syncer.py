"""Resource/load syncer: periodic node->head reports + head->node views.

Reference: RaySyncer (src/ray/common/ray_syncer/ray_syncer.h) — versioned
RESOURCE_VIEW / COMMANDS streams between raylets and the GCS, which then
re-broadcasts the merged cluster view. The topology here is the same
hub-and-spoke (every daemon syncs with the head; the head fans the merged
view back out); messages are versioned so stale updates are dropped.

Daemon side: :class:`NodeSyncer` thread sends a load snapshot (object
store occupancy, worker count, OS load) every period. Head side:
``Head.on_node_sync`` merges into ``node_loads`` (surfaced by the state
API), and membership changes broadcast a ``cluster_view`` message each
daemon retains for peer selection.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict


def collect_load(node) -> Dict[str, Any]:
    """Snapshot one node's load (daemon side)."""
    store = node.store
    try:
        load1 = os.getloadavg()[0]
    except OSError:  # pragma: no cover
        load1 = 0.0
    return {
        "ts": time.time(),
        "queue_depth": len(getattr(node, "_local_queue", ()) or ()),
        # in-flight direct-task arg leases: the head defers cluster-wide
        # deletes behind these (owner-side pinning's daemon-visible half)
        "leases": node.lease_snapshot() if hasattr(node, "lease_snapshot")
        else [],
        "store_capacity": store.capacity,
        "store_used": int(getattr(store.arena.allocator, "bytes_allocated",
                                  lambda: 0)())
        if store.arena.allocator else 0,
        "num_workers": len(getattr(node, "workers", []) or []),
        "os_load_1m": load1,
        "pid": os.getpid(),
    }


class NodeSyncer:
    """Daemon-side reporter: ships load snapshots on a fixed period."""

    def __init__(self, remote_head, node, period_s: float = 1.0):
        self._head = remote_head
        self._node = node
        self._period = period_s
        self._version = 0
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-syncer")
        self._thread.start()

    def _loop(self) -> None:
        from .fault_injection import should_drop

        while not self._stopped.wait(self._period):
            if should_drop("daemon.sync"):
                continue  # chaos point: lose this snapshot
            self._version += 1
            try:
                snap = collect_load(self._node)
                snap["version"] = self._version
                # head-incarnation echo: a restarted head that sees a
                # stale epoch on the sync tells the daemon to reregister
                snap["epoch"] = getattr(self._head, "epoch", None)
                self._head._send("sync", snap)
            except Exception:
                # transient (head bouncing, RemoteHead mid-reconnect):
                # keep reporting — only a declared-dead link ends the loop
                if getattr(self._head, "stopped", None) is not None \
                        and self._head.stopped.is_set():
                    return

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=2.0)  # event wait: exits immediately
