"""@remote functions.

Analog of ``python/ray/remote_function.py`` in the reference: wraps a Python
function, registers its cloudpickle payload in the GCS function table once
(reference: function_manager.py export), and turns ``.remote(...)`` calls into
TaskSpec submissions. Small args are inlined into the spec; args above the
inline threshold are promoted to the object store and passed by reference
(reference: core_worker.cc:2166 + max_direct_call_object_size).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from . import serialization
from .config import global_config
from .ids import ObjectID, TaskID
from .object_ref import ObjectRef
from .resources import parse_task_resources
from .task_spec import SchedulingStrategy, TaskSpec


def _function_id(payload: bytes) -> str:
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


def prepare_args(runtime, args, kwargs) -> Tuple[list, dict, List[ObjectRef]]:
    """Returns (args, kwargs, keepalive). ``keepalive`` holds the ObjectRefs
    of promoted large args — the caller must keep it alive until after
    submission, when the head pins them via spec.pinned_args (otherwise GC
    could delete the object between put and submit)."""
    cfg = global_config()
    keepalive: List[ObjectRef] = []

    def conv(a):
        if isinstance(a, ObjectRef):
            keepalive.append(a)  # pin user refs too: the caller may drop
            return ("ref", a.id)  # theirs while the task is still pending
        s = serialization.serialize(a)
        if s.total_bytes > cfg.max_direct_call_object_size:
            ref = runtime.put(a)
            keepalive.append(ref)
            return ("ref", ref.id)
        return ("v", s.to_bytes())

    out_args = [conv(a) for a in args]
    out_kwargs = {k: conv(v) for k, v in kwargs.items()}
    return out_args, out_kwargs, keepalive


def resolve_scheduling_strategy(strategy) -> SchedulingStrategy:
    if strategy is None or strategy == "DEFAULT":
        return SchedulingStrategy("DEFAULT")
    if strategy == "SPREAD":
        return SchedulingStrategy("SPREAD")
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    # duck-typed public strategies from util.scheduling_strategies
    kind = type(strategy).__name__
    if kind == "NodeAffinitySchedulingStrategy":
        nid = strategy.node_id
        return SchedulingStrategy("NODE_AFFINITY",
                                  node_id=nid if isinstance(nid, str) else nid,
                                  soft=strategy.soft)
    if kind == "PlacementGroupSchedulingStrategy":
        pg = strategy.placement_group
        return SchedulingStrategy(
            "PLACEMENT_GROUP",
            placement_group_id=pg.id,
            bundle_index=strategy.placement_group_bundle_index
            if strategy.placement_group_bundle_index is not None else -1,
            capture_child_tasks=strategy.placement_group_capture_child_tasks or False,
        )
    raise ValueError(f"unsupported scheduling strategy {strategy!r}")


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        # Pickling is deferred to first .remote(): at decoration time the
        # defining module may still be mid-import, which would force
        # cloudpickle to capture by value with an incomplete globals dict
        # (later-defined helpers would raise NameError on the worker).
        self._payload: Optional[bytes] = None
        self._function_id: Optional[str] = None
        self._registered_with = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)

    def _materialize_payload(self) -> None:
        if self._payload is None:
            self._payload = cloudpickle.dumps(self._fn)
            self._function_id = _function_id(self._payload)

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        clone = RemoteFunction.__new__(RemoteFunction)
        clone._fn = self._fn
        clone._options = merged
        clone._payload = self._payload
        clone._function_id = self._function_id
        clone._registered_with = self._registered_with
        clone.__name__ = self.__name__
        clone.__doc__ = self.__doc__
        return clone

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self.__name__}() cannot be called directly; "
            f"use {self.__name__}.remote()."
        )

    def __getstate__(self):
        # picklable across processes/storage: drop the runtime binding
        # (re-registers lazily on the other side)
        d = self.__dict__.copy()
        d["_registered_with"] = None
        return d

    def bind(self, *args, **kwargs):
        """Author a task-DAG node for workflows (reference: dag_node.py
        bind / workflow DAG authoring)."""
        from ray_tpu.workflow.api import FunctionNode

        return FunctionNode(self, args, kwargs)

    def _ensure_registered(self, runtime) -> None:
        self._materialize_payload()
        if self._registered_with is not runtime:
            runtime.register_function(self._function_id, self._payload)
            self._registered_with = runtime

    def remote(self, *args, **kwargs):
        from .runtime import get_current_runtime

        runtime = get_current_runtime()
        if runtime is None:
            raise RuntimeError("ray_tpu.init() has not been called")
        self._ensure_registered(runtime)
        opt = self._options
        out_args, out_kwargs, keepalive = prepare_args(runtime, args, kwargs)
        from .runtime_env import pack_runtime_env

        runtime_env = pack_runtime_env(opt.get("runtime_env"), runtime)
        num_returns = opt.get("num_returns", 1)
        streaming = num_returns in ("streaming", "dynamic")
        if streaming:
            num_returns = 1  # primary return carries the final item count
        spec = TaskSpec(
            task_id=runtime.next_task_id(),
            job_id=runtime.runtime_context()["job_id"],
            function_id=self._function_id,
            function_name=self.__name__,
            args=out_args,
            kwargs=out_kwargs,
            num_returns=num_returns,
            streaming=streaming,
            resources=parse_task_resources(
                num_cpus=opt.get("num_cpus"),
                num_tpus=opt.get("num_tpus"),
                num_gpus=opt.get("num_gpus"),
                resources=opt.get("resources"),
                memory=opt.get("memory"),
                default_num_cpus=1.0,
            ),
            max_retries=opt.get("max_retries", 3),
            retry_exceptions=bool(opt.get("retry_exceptions", False)),
            scheduling_strategy=resolve_scheduling_strategy(
                opt.get("scheduling_strategy")),
            runtime_env=runtime_env,
            pinned_args=[r.id for r in keepalive],
        )
        # explicit soft-locality hint (e.g. the data executor dispatching a
        # map task to the node holding its input block); the head's
        # arg-size inference only runs when this is unset
        loc = opt.get("locality_hex")
        if loc is not None:
            spec.locality_hex = loc
        from ray_tpu.util.tracing import current_context

        spec.trace_ctx = current_context()
        refs = runtime.submit_task(spec)
        if streaming:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, refs[0])
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs
