"""Cluster scheduler: resource-based node selection + placement groups.

Analog of the reference's two-level scheduler
(``src/ray/raylet/scheduling/``): a cluster resource view picks a node
(`ClusterResourceScheduler` + policies), then the node's local dispatch binds
resource instances and a worker. Policies implemented (reference
``policy/``): hybrid (pack until ``scheduler_spread_threshold`` utilization,
then least-utilized with top-k randomization), SPREAD (round-robin),
node-affinity, and placement-group bundle scheduling with
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD (reference:
bundle_scheduling_policy.cc, 2-phase reserve/commit in
gcs_placement_group_scheduler.cc).

TPU-topology awareness: nodes carry labels (e.g. ``tpu-slice``,
``tpu-topology``) and unit-instance TPU resources; STRICT_SPREAD over
slice hosts is what the Train layer uses to gang-schedule one worker per
host of a pod slice.
"""

from __future__ import annotations

import random
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .config import global_config
from .exceptions import PlacementGroupError
from .ids import PlacementGroupID
from .resources import NodeResources, ResourceSet
from .task_spec import TaskSpec


@dataclass
class Bundle:
    index: int
    resources: ResourceSet
    node_hex: Optional[str] = None
    # resources currently available inside the reservation
    available: Optional[Dict[str, int]] = None
    # unit-instance indices reserved from the node (e.g. TPU chip ids) and
    # the subset currently free inside the bundle
    reserved_instances: Dict[str, List[int]] = field(default_factory=dict)
    free_instances: Dict[str, List[int]] = field(default_factory=dict)

    def fits(self, req: ResourceSet) -> bool:
        return all(self.available.get(k, 0) >= v for k, v in req)

    def acquire(self, req: ResourceSet) -> Dict[str, List[int]]:
        """Take resources + concrete device indices from the reservation."""
        from .resources import from_fixed

        binding: Dict[str, List[int]] = {}
        for k, v in req:
            self.available[k] = self.available.get(k, 0) - v
            if k in self.free_instances:
                whole = int(from_fixed(v))
                if whole > 0:
                    binding[k] = self.free_instances[k][:whole]
                    self.free_instances[k] = self.free_instances[k][whole:]
                elif self.free_instances[k]:
                    # fractional: share the last free instance (see
                    # NodeResources.allocate for rationale)
                    binding[k] = self.free_instances[k][-1:]
        return binding

    def release(self, req: ResourceSet, binding: Optional[Dict[str, List[int]]] = None) -> None:
        from .resources import from_fixed

        for k, v in req:
            self.available[k] = self.available.get(k, 0) + v
            if binding and k in binding and int(from_fixed(v)) > 0:
                self.free_instances[k] = sorted(
                    self.free_instances.get(k, []) + binding[k])


@dataclass
class PlacementGroup:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str = "PACK"
    state: str = "PENDING"  # PENDING | CREATED | REMOVED
    name: str = ""
    ready_event: threading.Event = field(default_factory=threading.Event)


class ClusterScheduler:
    """Holds the cluster resource view; picks nodes; queues pending work."""

    def __init__(self, dispatch_fn: Callable[[str, TaskSpec, dict], None]):
        # dispatch_fn(node_hex, spec, instance_binding) actually executes.
        self._dispatch = dispatch_fn
        self._nodes: Dict[str, NodeResources] = {}
        self._node_order: List[str] = []
        from .lock_debug import tracked_rlock

        self._lock = tracked_rlock("ClusterScheduler._lock")
        self._pending: deque = deque()
        self._pgs: Dict[PlacementGroupID, PlacementGroup] = {}
        self._pending_pgs: deque = deque()
        # infeasibility memo, both cleared when the cluster shape changes:
        # sigs already reported infeasible, and sigs known feasible (so the
        # totals scan runs once per sig per shape, not on every rescan of
        # the 1M-round pending-queue hot path)
        self._infeasible_reported: set = set()
        self._feasible_sigs: set = set()
        self._spread_rr = 0
        self._wake = threading.Condition(self._lock)
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True, name="scheduler")
        self._thread.start()

    # ---- node membership -------------------------------------------------

    def add_node(self, node_hex: str, resources: NodeResources) -> None:
        with self._lock:
            self._nodes[node_hex] = resources
            self._node_order.append(node_hex)
            self._infeasible_reported.clear()  # new shape: re-evaluate
            self._feasible_sigs.clear()
            self._wake.notify_all()

    def remove_node(self, node_hex: str) -> None:
        with self._lock:
            self._nodes.pop(node_hex, None)
            if node_hex in self._node_order:
                self._node_order.remove(node_hex)
            # a shrunk cluster can turn feasible sigs infeasible (already-
            # reported ones stay infeasible: shrinking never adds capacity)
            self._feasible_sigs.clear()
            # kill reservations on that node
            for pg in self._pgs.values():
                for b in pg.bundles:
                    if b.node_hex == node_hex:
                        b.node_hex = None
            self._wake.notify_all()

    def node_resources(self, node_hex: str) -> Optional[NodeResources]:
        with self._lock:
            return self._nodes.get(node_hex)

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for nr in self._nodes.values():
                for k, v in nr.view().items():
                    out[k] = out.get(k, 0) + v
            return out

    def total_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for nr in self._nodes.values():
                for k, v in nr.total.to_dict().items():
                    out[k] = out.get(k, 0) + v
            return out

    def pending_demand(self) -> List[Dict[str, float]]:
        """Resource asks of queued (unplaced) tasks + unreserved PG bundles
        — the autoscaler's input (reference: resource_demand_scheduler.py
        consuming GCS load reports)."""
        with self._lock:
            out = [dict(spec.resources.to_dict()) for spec in self._pending]
            for pg in self._pending_pgs:
                for b in pg.bundles:
                    if b.node_hex is None:
                        out.append(dict(b.resources.to_dict()))
            return out

    def idle_nodes(self) -> List[str]:
        """Nodes with zero resource utilization (no tasks/actors/bundles)."""
        with self._lock:
            return [h for h, nr in self._nodes.items()
                    if nr.utilization() <= 0.0]

    # ---- task scheduling -------------------------------------------------

    def submit(self, spec: TaskSpec) -> None:
        with self._lock:
            self._pending.append(spec)
            self._wake.notify_all()

    def release(self, node_hex: str, spec: TaskSpec, binding: dict) -> None:
        """Return a finished task's resources; wakes the dispatch loop."""
        with self._lock:
            self._release_locked(node_hex, spec, binding)
            self._wake.notify_all()

    def release_partial(self, node_hex: str, spec: TaskSpec,
                        rset: ResourceSet,
                        binding: Optional[dict] = None) -> None:
        """Return an explicit subset of a task's reservation — the actor
        scheduling-only-CPU path (reference: actors need 1 CPU to
        schedule, hold 0 while alive). PG-aware like release()."""
        with self._lock:
            self._release_locked(node_hex, spec, binding, rset=rset)
            self._wake.notify_all()

    def _release_locked(self, node_hex: str, spec: TaskSpec, binding: dict,
                        rset: Optional[ResourceSet] = None) -> None:
        rset = spec.resources if rset is None else rset
        st = spec.scheduling_strategy
        if st.kind == "PLACEMENT_GROUP" and st.placement_group_id in self._pgs:
            pg = self._pgs[st.placement_group_id]
            if pg.state == "REMOVED":
                # bundle reservation already returned its unused part;
                # the in-use part comes back directly to the node here
                nr = self._nodes.get(node_hex)
                if nr is not None:
                    nr.release(rset, binding)
            elif 0 <= st.bundle_index < len(pg.bundles):
                pg.bundles[st.bundle_index].release(rset, binding)
        else:
            nr = self._nodes.get(node_hex)
            if nr is not None:
                nr.release(rset, binding)

    def complete_and_next(self, node_hex: str, spec: TaskSpec, binding: dict):
        """Release a finished task's resources and, in the same lock hold,
        try to place the head-of-queue pending task — returning it for the
        caller (the node reader thread) to dispatch directly.

        This is the lease-caching fast path (reference:
        normal_task_submitter.h:145 worker_to_lease_entry_): for streams of
        same-shape tasks, completion -> next dispatch never touches the
        scheduler thread, so no cv wakeup latency sits between tasks.
        """
        with self._lock:
            self._release_locked(node_hex, spec, binding)
            if self._pending and not self._stopped:
                placed = self._try_place_locked(self._pending[0])
                if placed is not None:
                    self._pending.popleft()
                    return placed
            self._wake.notify_all()
        return None

    def kick(self) -> None:
        with self._lock:
            self._wake.notify_all()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._wake.notify_all()
        self._thread.join(timeout=2.0)  # loop re-checks _stopped on wake

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                progress = self._try_schedule_pgs_locked()
                ready: List[Tuple[str, TaskSpec, dict]] = []
                still_pending = deque()
                # Placements within a round only consume resources, so once a
                # request signature fails to place, every later spec with the
                # same signature fails too — skip them. Turns the O(queue)
                # rescan per completion into O(1) for homogeneous batches
                # (the 1M-calls-for-2k-tasks hot spot in bench_core.py).
                failed_sigs = set()
                infeasible: List[TaskSpec] = []
                while self._pending:
                    spec = self._pending.popleft()
                    sig = self._request_sig(spec)
                    if sig in failed_sigs:
                        still_pending.append(spec)
                        continue
                    placed = self._try_place_locked(spec)
                    if placed is None:
                        failed_sigs.add(sig)
                        still_pending.append(spec)
                        if (self._nodes
                                and sig not in self._feasible_sigs
                                and sig not in self._infeasible_reported):
                            if self._infeasible_locked(spec):
                                self._infeasible_reported.add(sig)
                                infeasible.append(spec)
                            else:
                                self._feasible_sigs.add(sig)
                    else:
                        ready.append(placed)
                self._pending = still_pending
                if not ready and not progress:
                    self._wake.wait(timeout=0.25)
            for spec in infeasible:  # emit outside the scheduler lock
                self._emit_infeasible(spec)
            for node_hex, spec, binding in ready:
                try:
                    self._dispatch(node_hex, spec, binding)
                except Exception:
                    with self._lock:
                        nr = self._nodes.get(node_hex)
                        if nr is not None:
                            nr.release(spec.resources, binding)

    def _infeasible_locked(self, spec: TaskSpec) -> bool:
        """True when no node's TOTAL resources can ever fit the request —
        distinct from transient unavailability (reference: the raylet's
        infeasible-task queue + its autoscaler warning)."""
        ask = {k: v for k, v in spec.resources.to_dict().items() if v > 0}
        if not ask:
            return False
        for nr in self._nodes.values():
            total = nr.total.to_dict()
            if all(total.get(k, 0) >= v for k, v in ask.items()):
                return False
        return True

    def _emit_infeasible(self, spec: TaskSpec) -> None:
        from ray_tpu.util import events as events_mod

        events_mod.emit(
            "WARNING", events_mod.SOURCE_SCHEDULER,
            f"infeasible request: {spec.function_name} asks "
            f"{spec.resources.to_dict()} but no node can ever fit it",
            entity_id=spec.task_id.hex(),
            resources=spec.resources.to_dict(),
            function=spec.function_name)

    @staticmethod
    def _request_sig(spec: TaskSpec):
        """Hashable placement-equivalence key: same sig => same placeability
        given fixed cluster resources. Cached on the spec."""
        sig = getattr(spec, "_sched_sig", None)
        if sig is None:
            st = spec.scheduling_strategy
            sig = (tuple(sorted(spec.resources.to_dict().items())), st.kind,
                   getattr(st, "placement_group_id", None),
                   getattr(st, "bundle_index", -1),
                   str(getattr(st, "node_id", None)),
                   getattr(st, "soft", False),
                   spec.locality_hex)
            spec._sched_sig = sig
        return sig

    def _try_place_locked(self, spec: TaskSpec) -> Optional[Tuple[str, TaskSpec, dict]]:
        st = spec.scheduling_strategy
        if st.kind == "PLACEMENT_GROUP":
            pg = self._pgs.get(st.placement_group_id)
            if pg is None or pg.state == "REMOVED":
                return None
            if pg.state != "CREATED":
                return None
            indices = (
                [st.bundle_index]
                if st.bundle_index >= 0
                else list(range(len(pg.bundles)))
            )
            for i in indices:
                b = pg.bundles[i]
                if b.node_hex is not None and b.fits(spec.resources):
                    binding = b.acquire(spec.resources)
                    if st.bundle_index < 0:
                        st.bundle_index = i
                        spec._sched_sig = None  # sig keyed on bundle_index
                    return b.node_hex, spec, binding
            return None

        if st.kind == "NODE_AFFINITY" and st.node_id is not None:
            hexes = [st.node_id.hex() if isinstance(st.node_id, bytes) else st.node_id]
            if not st.soft:
                nr = self._nodes.get(hexes[0])
                if nr is None:
                    return None
                binding = nr.allocate(spec.resources)
                if binding is None:
                    return None
                return hexes[0], spec, binding
            # soft: fall through to default with preference
            preferred = hexes[0]
        else:
            # soft data-locality preference (reference: lease_policy.h:56
            # LocalityAwareLeasePolicy — lease from the largest-arg node)
            preferred = spec.locality_hex

        candidates = self._feasible_locked(spec.resources)
        if not candidates:
            return None
        if st.kind == "SPREAD":
            order = candidates[self._spread_rr % len(candidates):] + \
                candidates[: self._spread_rr % len(candidates)]
            self._spread_rr += 1
            chosen = order[0]
        else:
            chosen = self._hybrid_pick_locked(candidates, preferred)
        nr = self._nodes[chosen]
        binding = nr.allocate(spec.resources)
        if binding is None:
            return None
        return chosen, spec, binding

    def _feasible_locked(self, req: ResourceSet) -> List[str]:
        return [
            h for h in self._node_order
            if h in self._nodes and self._nodes[h].can_fit(req)
        ]

    def _hybrid_pick_locked(self, candidates: List[str], preferred: Optional[str]) -> str:
        """Reference hybrid_scheduling_policy.cc: pack onto low-utilization
        nodes in fixed order; above the spread threshold, choose randomly
        among the top-k least utilized."""
        cfg = global_config()
        if preferred and preferred in candidates:
            return preferred
        below = [h for h in candidates
                 if self._nodes[h].utilization() < cfg.scheduler_spread_threshold]
        if below:
            return below[0]
        ranked = sorted(candidates, key=lambda h: self._nodes[h].utilization())
        k = max(int(len(ranked) * cfg.scheduler_top_k_fraction),
                cfg.scheduler_top_k_absolute)
        return random.choice(ranked[:k])

    # ---- placement groups ------------------------------------------------

    def create_placement_group(
        self,
        bundles: List[Dict[str, float]],
        strategy: str = "PACK",
        name: str = "",
        pg_id: Optional[PlacementGroupID] = None,
    ) -> PlacementGroup:
        """``pg_id`` is supplied only by head-restart recovery, which
        re-creates durable placement specs under their ORIGINAL ids so
        recovered actors' scheduling strategies still resolve."""
        pg = PlacementGroup(
            pg_id=pg_id or PlacementGroupID.from_random(),
            bundles=[Bundle(i, ResourceSet(b)) for i, b in enumerate(bundles)],
            strategy=strategy,
            name=name,
        )
        with self._lock:
            self._pgs[pg.pg_id] = pg
            self._pending_pgs.append(pg)
            self._wake.notify_all()
        persist = getattr(self, "persist_pg", None)
        if persist is not None:
            persist(pg.pg_id.hex(),
                    {"bundles": bundles, "strategy": strategy, "name": name})
        return pg

    def get_placement_group(self, pg_id: PlacementGroupID) -> Optional[PlacementGroup]:
        with self._lock:
            return self._pgs.get(pg_id)

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        from .resources import ResourceSet

        persist = getattr(self, "persist_pg", None)
        if persist is not None:
            persist(pg_id.hex(), None)  # retire the durable spec
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None or pg.state == "REMOVED":
                return
            pg.state = "REMOVED"
            for b in pg.bundles:
                if (b.node_hex is not None and b.node_hex in self._nodes
                        and b.available is not None):
                    # return only the unused part now (with its free device
                    # indices); resources held by still-running tasks come
                    # back via release()
                    self._nodes[b.node_hex].release(
                        ResourceSet._from_fixed_map(b.available),
                        binding=b.free_instances)
                    b.available = {k: 0 for k in b.available}
                    b.free_instances = {}
            self._wake.notify_all()

    def _try_schedule_pgs_locked(self) -> bool:
        """2-phase: tentatively pick nodes for all bundles; commit only if all
        fit (reference: gcs_placement_group_scheduler.cc prepare/commit)."""
        progress = False
        still = deque()
        while self._pending_pgs:
            pg = self._pending_pgs.popleft()
            if pg.state == "REMOVED":
                continue
            plan = self._plan_bundles_locked(pg)
            if plan is None:
                still.append(pg)
                continue
            for b, node_hex in zip(pg.bundles, plan):
                nr = self._nodes[node_hex]
                inst = nr.allocate(b.resources) or {}  # commit reservation
                b.node_hex = node_hex
                b.available = {k: v for k, v in b.resources}
                b.reserved_instances = {k: list(v) for k, v in inst.items()}
                b.free_instances = {k: list(v) for k, v in inst.items()}
            pg.state = "CREATED"
            pg.ready_event.set()
            progress = True
        self._pending_pgs = still
        return progress

    def _plan_bundles_locked(self, pg: PlacementGroup) -> Optional[List[str]]:
        # Work on a scratch copy of availability so planning doesn't mutate.
        scratch: Dict[str, Dict[str, int]] = {
            h: dict(nr.available) for h, nr in self._nodes.items()
        }

        def fits(h: str, rs: ResourceSet) -> bool:
            return all(scratch[h].get(k, 0) >= v for k, v in rs)

        def take(h: str, rs: ResourceSet) -> None:
            for k, v in rs:
                scratch[h][k] = scratch[h].get(k, 0) - v

        nodes = list(self._node_order)
        if not nodes:
            return None
        plan: List[str] = []
        if pg.strategy == "STRICT_PACK":
            for h in nodes:
                trial = dict(scratch[h])
                ok = True
                for b in pg.bundles:
                    if all(trial.get(k, 0) >= v for k, v in b.resources):
                        for k, v in b.resources:
                            trial[k] = trial.get(k, 0) - v
                    else:
                        ok = False
                        break
                if ok:
                    return [h] * len(pg.bundles)
            return None
        if pg.strategy == "STRICT_SPREAD":
            if len(nodes) < len(pg.bundles):
                return None
            used = set()
            for b in pg.bundles:
                placed = None
                for h in nodes:
                    if h in used:
                        continue
                    if fits(h, b.resources):
                        placed = h
                        break
                if placed is None:
                    return None
                used.add(placed)
                take(placed, b.resources)
                plan.append(placed)
            return plan
        # PACK / SPREAD: best-effort orderings
        prefer_spread = pg.strategy == "SPREAD"
        for i, b in enumerate(pg.bundles):
            ordered = nodes if not prefer_spread else nodes[i % len(nodes):] + nodes[: i % len(nodes)]
            placed = None
            for h in ordered:
                if fits(h, b.resources):
                    placed = h
                    break
            if placed is None:
                return None
            take(placed, b.resources)
            plan.append(placed)
        return plan
