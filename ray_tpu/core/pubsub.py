"""General pubsub: named channels, seq-cursored subscribers.

Analog of the reference's pubsub service
(``src/ray/pubsub/publisher.h:296`` Publisher/SubscriberState + the
``SubscriberService`` channels in pubsub.proto) — the round-3 audit's
"hard-wired broadcast tags, no general channel/subscriber service" gap.

The broker lives on the head; every message gets a per-channel sequence
number and lands in a bounded ring. Subscribers are CURSORS, not
connections: a poll(channel, cursor, timeout) blocks on the broker's cv
until messages past the cursor exist (or the bounded round ends), so
subscribers survive head-link blips, duplicate nothing, and cost the
broker zero state (the reference's long-poll semantics without per-
subscriber server bookkeeping). A slow subscriber that falls more than
the ring capacity behind observes a gap (returned explicitly) instead of
unbounded buffering — the same overflow policy as the reference's
publisher buffers.

Public surface: ``ray_tpu.util.pubsub.publish/subscribe``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple


class PubsubBroker:
    """Head-side channel registry (one per cluster)."""

    def __init__(self, ring_capacity: int = 10_000):
        self._cap = ring_capacity
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # channel -> (next_seq, ring of (seq, payload))
        self._channels: Dict[str, Tuple[int, deque]] = {}
        self._last_pub: Dict[str, float] = {}

    def publish(self, channel: str, payload: Any) -> int:
        """Append; returns the message's sequence number."""
        with self._cv:
            seq, ring = self._channels.get(channel, (0, None))
            if ring is None:
                ring = deque(maxlen=self._cap)
            ring.append((seq, payload))
            self._channels[channel] = (seq + 1, ring)
            self._last_pub[channel] = time.monotonic()
            self._cv.notify_all()
            return seq

    def gc(self, idle_ttl_s: float) -> int:
        """Drop the payload rings of channels idle past the TTL; the
        next_seq tombstone stays (an int), so late subscribers' cursors
        remain valid and a future publish continues the sequence
        (reference: publisher buffers are garbage-collected; the head
        must not retain dead channels' payloads forever)."""
        now = time.monotonic()
        dropped = 0
        with self._lock:
            for ch, (seq, ring) in list(self._channels.items()):
                if ring is None or not ring:
                    continue
                if now - self._last_pub.get(ch, 0.0) >= idle_ttl_s:
                    self._channels[ch] = (seq, None)
                    dropped += 1
        return dropped

    def poll(self, channel: str, cursor: int, timeout: float,
             max_messages: int = 1000):
        """One bounded long-poll round.

        Returns (messages, next_cursor, gap): ``messages`` = payloads
        with seq >= cursor (at most max_messages); ``gap`` is True when
        the ring already dropped messages the cursor still expected
        (subscriber fell behind by more than the ring capacity).
        """
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cv:
            while True:
                seq, ring = self._channels.get(channel, (0, None))
                if ring:
                    oldest = ring[0][0]
                    if seq > cursor:
                        gap = cursor < oldest
                        start = max(cursor, oldest)
                        msgs = [p for s, p in ring
                                if s >= start][:max_messages]
                        return msgs, start + len(msgs), gap
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], cursor, False
                self._cv.wait(min(remaining, 0.5))

    def cursor(self, channel: str) -> int:
        """The next-seq position (subscribe-from-now semantics)."""
        with self._lock:
            return self._channels.get(channel, (0, None))[0]
