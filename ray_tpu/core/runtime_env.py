"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Analog of the reference's runtime-env subsystem
(python/ray/_private/runtime_env/ + agent/runtime_env_agent.py:161):
directories are zipped at submission, shipped through the GCS KV store,
and materialized once per worker host into a content-addressed cache;
env_vars apply around execution (set-and-restore for shared plain-task
workers, permanent for actor-dedicated workers).

Supported keys: ``env_vars`` (dict), ``working_dir`` (local dir path),
``py_modules`` (list of local dir paths), ``pip`` (list of requirement
strings / local package paths, or ``{"packages": [...], "pip_install_
options": [...]}``) — a content-addressed virtualenv is created once per
host per requirement set (reference: runtime_env/pip.py) and its
site-packages activates around execution. The venv uses
``--system-site-packages`` so jax/the framework stay importable;
container/conda isolation is out of scope (workers share the
interpreter).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional, Tuple

_KV_NS = "runtime_env"
_MAX_ZIP = 100 * 1024 * 1024
# abspath -> (fingerprint, uploaded-ref): skip re-zipping an unchanged dir
# on every .remote() call (submission-throughput killer otherwise)
_upload_cache: Dict[str, Tuple[tuple, dict]] = {}


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(base):
            for f in files:
                if f.endswith(".pyc") or "__pycache__" in root:
                    continue
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, base))
    data = buf.getvalue()
    if len(data) > _MAX_ZIP:
        raise ValueError(
            f"runtime_env dir {path!r} zips to {len(data)}B "
            f"(limit {_MAX_ZIP}B)")
    return data


def _dir_fingerprint(base: str) -> tuple:
    """Cheap change detector: (count, total size, max mtime) over files."""
    n = total = 0
    latest = 0.0
    for root, _dirs, files in os.walk(base):
        for f in files:
            if f.endswith(".pyc") or "__pycache__" in root:
                continue
            try:
                st = os.stat(os.path.join(root, f))
            except OSError:
                continue
            n += 1
            total += st.st_size
            latest = max(latest, st.st_mtime)
    return (n, total, latest)


def pack_runtime_env(env: Optional[dict], runtime) -> Optional[dict]:
    """Driver/submitter side: replace local paths with KV references."""
    if not env:
        return env
    out = dict(env)

    def upload(path: str) -> dict:
        base = os.path.abspath(path)
        fp = _dir_fingerprint(base)
        cached = _upload_cache.get(base)
        if cached is not None and cached[0] == fp:
            # shutdown()+init() recreates the KV store: confirm the
            # package still exists before trusting the cached ref
            if runtime.kv("exists", cached[1]["kv_key"].encode(), _KV_NS):
                return cached[1]
        data = _zip_dir(path)
        digest = hashlib.blake2b(data, digest_size=16).hexdigest()
        key = f"pkg_{digest}".encode()
        if not runtime.kv("exists", key, _KV_NS):
            runtime.kv("put", key, data, _KV_NS, True)
        ref = {"kv_key": key.decode(), "hash": digest,
               "basename": os.path.basename(base)}
        _upload_cache[base] = (fp, ref)
        return ref

    wd = out.get("working_dir")
    if isinstance(wd, str):
        out["working_dir"] = upload(wd)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [upload(m) if isinstance(m, str) else m
                             for m in mods]
    return out


def _materialize(ref: dict, runtime) -> str:
    """Extract a KV-stored zip into the host-local content cache."""
    import fcntl

    cache_root = os.path.join("/tmp", "raytpu_runtime_env")
    os.makedirs(cache_root, exist_ok=True)
    dest = os.path.join(cache_root, ref["hash"])
    marker = dest + ".ok"
    if os.path.exists(marker):
        return dest
    with open(dest + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return dest
        data = runtime.kv("get", ref["kv_key"].encode(), _KV_NS)
        if data is None:
            raise RuntimeError(
                f"runtime_env package {ref['kv_key']} missing from KV")
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(dest)
        open(marker, "w").close()
    return dest


def _materialize_pip_env(pip_spec, runtime) -> str:
    """Create (once per host) the venv for a requirement set; returns its
    site-packages path (reference: runtime_env/pip.py — per-env-hash venv
    with delete-on-failure + cross-process locking)."""
    import fcntl
    import subprocess

    if isinstance(pip_spec, dict):
        reqs = list(pip_spec.get("packages") or [])
        opts = list(pip_spec.get("pip_install_options") or [])
    else:
        reqs = list(pip_spec)
        opts = []
    digest = hashlib.blake2b(
        ("\n".join(sorted(reqs) + sorted(opts))).encode(),
        digest_size=12).hexdigest()
    cache_root = os.path.join("/tmp", "raytpu_runtime_env")
    os.makedirs(cache_root, exist_ok=True)
    dest = os.path.join(cache_root, f"pip-{digest}")
    marker = dest + ".ok"

    def site_packages() -> str:
        v = f"python{sys.version_info.major}.{sys.version_info.minor}"
        return os.path.join(dest, "lib", v, "site-packages")

    if os.path.exists(marker):
        return site_packages()
    with open(dest + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return site_packages()
        import shutil
        import venv

        shutil.rmtree(dest, ignore_errors=True)  # prior failed attempt
        try:
            venv.create(dest, system_site_packages=True, with_pip=True,
                        symlinks=True)
            # when THIS interpreter itself lives in a venv (/opt/venv),
            # system_site_packages points past it to the base python —
            # bridge our site-packages in via a .pth so pip's build
            # backend (setuptools) and the framework stay importable
            host_sps = [p for p in sys.path if p.endswith("site-packages")
                        and os.path.isdir(p)]
            if host_sps:
                with open(os.path.join(site_packages(),
                                       "_raytpu_host.pth"), "w") as f:
                    f.write("\n".join(host_sps) + "\n")
            pip = os.path.join(dest, "bin", "pip")
            proc = subprocess.run(
                [pip, "install", "--disable-pip-version-check",
                 "--no-input"] + opts + reqs,
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip install failed for runtime_env {reqs}:\n"
                    + proc.stderr[-2000:])
            open(marker, "w").close()
        except BaseException:
            shutil.rmtree(dest, ignore_errors=True)
            raise
    return site_packages()


def apply_runtime_env(env: Optional[dict], runtime):
    """Worker side: apply before execution; returns a restore() callable
    (no-op when nothing was applied)."""
    if not env:
        return lambda: None
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd: Optional[str] = None
    added_paths: List[str] = []

    def restore():
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        for p in added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if added_paths:
            # modules imported FROM the env must not leak into later
            # tasks through the sys.modules cache (the path alone is not
            # the isolation boundary)
            roots = tuple(os.path.abspath(p) + os.sep for p in added_paths)
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and os.path.abspath(f).startswith(roots):
                    sys.modules.pop(name, None)

    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = str(v)

        wd = env.get("working_dir")
        if isinstance(wd, dict):
            path = _materialize(wd, runtime)
            saved_cwd = os.getcwd()
            os.chdir(path)
            sys.path.insert(0, path)
            added_paths.append(path)

        for mod in env.get("py_modules") or ():
            if isinstance(mod, dict):
                path = _materialize(mod, runtime)
                sys.path.insert(0, path)
                added_paths.append(path)

        pip_spec = env.get("pip")
        if pip_spec:
            sp = _materialize_pip_env(pip_spec, runtime)
            sys.path.insert(0, sp)
            added_paths.append(sp)
    except BaseException:
        restore()  # partial application must not leak into later tasks
        raise

    return restore
