"""Runtime environments: a plugin seam + the built-in env plugins.

Analog of the reference's runtime-env subsystem
(python/ray/_private/runtime_env/ + agent/runtime_env_agent.py:161) with
its plugin interface (runtime_env/plugin.py RuntimeEnvPlugin): every
``runtime_env`` dict key is owned by a plugin with three hooks —

    pack(value, runtime)      submitter side: replace local paths with
                              content-addressed KV refs
    create(value, runtime)    worker side, once per host (plugins cache
                              by content hash): materialize, return a
                              context
    activate(context, state)  apply around execution; register undo via
                              the ActivationState

Built-ins registered through the same seam: ``env_vars``,
``working_dir``, ``py_modules``, ``pip`` (per-requirement-set venvs) and
``conda`` (env-yaml -> ``conda env create`` — honest error when no conda
executable exists, e.g. this zero-egress image). Third-party plugins
register via :func:`register_plugin` or the
``RAY_TPU_RUNTIME_ENV_PLUGINS`` env var (``module:Class,...``), which
worker processes load lazily (reference: RAY_RUNTIME_ENV_PLUGINS).

Isolation boundary: workers share the interpreter, so pip/conda envs
contribute ``sys.path`` entries (with module-cache purge on restore)
rather than a separate python; container images are out of scope.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Any, Callable, Dict, List, Optional, Tuple

_KV_NS = "runtime_env"
_MAX_ZIP = 100 * 1024 * 1024
# abspath -> (fingerprint, uploaded-ref): skip re-zipping an unchanged dir
# on every .remote() call (submission-throughput killer otherwise)
_upload_cache: Dict[str, Tuple[tuple, dict]] = {}

_CACHE_ROOT = os.path.join("/tmp", "raytpu_runtime_env")


# --------------------------------------------------------------------------- #
# shared helpers
# --------------------------------------------------------------------------- #


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(base):
            for f in files:
                if f.endswith(".pyc") or "__pycache__" in root:
                    continue
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, base))
    data = buf.getvalue()
    if len(data) > _MAX_ZIP:
        raise ValueError(
            f"runtime_env dir {path!r} zips to {len(data)}B "
            f"(limit {_MAX_ZIP}B)")
    return data


def _dir_fingerprint(base: str) -> tuple:
    """Cheap change detector: (count, total size, max mtime) over files."""
    n = total = 0
    latest = 0.0
    for root, _dirs, files in os.walk(base):
        for f in files:
            if f.endswith(".pyc") or "__pycache__" in root:
                continue
            try:
                st = os.stat(os.path.join(root, f))
            except OSError:
                continue
            n += 1
            total += st.st_size
            latest = max(latest, st.st_mtime)
    return (n, total, latest)


def _upload_dir(path: str, runtime) -> dict:
    base = os.path.abspath(path)
    fp = _dir_fingerprint(base)
    cached = _upload_cache.get(base)
    if cached is not None and cached[0] == fp:
        # shutdown()+init() recreates the KV store: confirm the
        # package still exists before trusting the cached ref
        if runtime.kv("exists", cached[1]["kv_key"].encode(), _KV_NS):
            return cached[1]
    data = _zip_dir(path)
    digest = hashlib.blake2b(data, digest_size=16).hexdigest()
    key = f"pkg_{digest}".encode()
    if not runtime.kv("exists", key, _KV_NS):
        runtime.kv("put", key, data, _KV_NS, True)
    ref = {"kv_key": key.decode(), "hash": digest,
           "basename": os.path.basename(base)}
    _upload_cache[base] = (fp, ref)
    return ref


def _materialize(ref: dict, runtime) -> str:
    """Extract a KV-stored zip into the host-local content cache."""
    import fcntl

    os.makedirs(_CACHE_ROOT, exist_ok=True)
    dest = os.path.join(_CACHE_ROOT, ref["hash"])
    marker = dest + ".ok"
    if os.path.exists(marker):
        return dest
    with open(dest + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return dest
        data = runtime.kv("get", ref["kv_key"].encode(), _KV_NS)
        if data is None:
            raise RuntimeError(
                f"runtime_env package {ref['kv_key']} missing from KV")
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(dest)
        open(marker, "w").close()
    return dest


# --------------------------------------------------------------------------- #
# plugin interface (reference: runtime_env/plugin.py RuntimeEnvPlugin)
# --------------------------------------------------------------------------- #


class ActivationState:
    """Undo journal one activation builds up; ``restore()`` unwinds it.
    Passed to every plugin's ``activate`` so custom plugins compose with
    the built-ins' set-and-restore semantics."""

    def __init__(self):
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        self._added_paths: List[str] = []
        self._deferred: List[Callable[[], None]] = []

    # -- plugin-facing mutators (each records its own undo) --

    def set_env(self, key: str, value: str) -> None:
        if key not in self._saved_env:
            self._saved_env[key] = os.environ.get(key)
        os.environ[key] = str(value)

    def chdir(self, path: str) -> None:
        if self._saved_cwd is None:
            self._saved_cwd = os.getcwd()
        os.chdir(path)

    def add_sys_path(self, path: str) -> None:
        sys.path.insert(0, path)
        self._added_paths.append(path)

    def defer(self, fn: Callable[[], None]) -> None:
        """Arbitrary custom undo, run during restore()."""
        self._deferred.append(fn)

    # -- runtime-facing --

    def restore(self) -> None:
        for fn in reversed(self._deferred):
            try:
                fn()
            except Exception:
                pass
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
        for p in self._added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._added_paths:
            # modules imported FROM the env must not leak into later
            # tasks through the sys.modules cache (the path alone is not
            # the isolation boundary)
            roots = tuple(os.path.abspath(p) + os.sep
                          for p in self._added_paths)
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and os.path.abspath(f).startswith(roots):
                    sys.modules.pop(name, None)


class RuntimeEnvPlugin:
    """One runtime_env key's implementation. Subclass + register."""

    name: str = ""
    # activation order: lower first (env_vars before path-contributing
    # plugins, so a plugin can read task env vars)
    priority: int = 10

    def pack(self, value: Any, runtime) -> Any:
        """Submitter side: make the value shippable (upload local paths)."""
        return value

    def create(self, value: Any, runtime) -> Any:
        """Worker side: materialize once per host; returns the context
        handed to ``activate``. Implementations cache by content hash."""
        return value

    def activate(self, context: Any, state: ActivationState) -> None:
        raise NotImplementedError


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}
_env_plugins_loaded = False


def register_plugin(plugin) -> None:
    """Register a plugin instance (or class — instantiated no-arg)."""
    if isinstance(plugin, type):
        plugin = plugin()
    if not plugin.name:
        raise ValueError("plugin needs a non-empty name")
    _PLUGINS[plugin.name] = plugin


def unregister_plugin(name: str) -> None:
    _PLUGINS.pop(name, None)


def _ensure_plugins() -> None:
    """Built-ins + RAY_TPU_RUNTIME_ENV_PLUGINS (module:Class,...) — the
    env var is how third-party plugins reach worker processes
    (reference: RAY_RUNTIME_ENV_PLUGINS)."""
    global _env_plugins_loaded
    for cls in (EnvVarsPlugin, WorkingDirPlugin, PyModulesPlugin,
                PipPlugin, CondaPlugin):
        if cls.name not in _PLUGINS:
            register_plugin(cls)
    if _env_plugins_loaded:
        return
    _env_plugins_loaded = True
    spec = os.environ.get("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        mod_name, _, cls_name = entry.partition(":")
        import importlib

        cls = getattr(importlib.import_module(mod_name), cls_name)
        if cls.name not in _PLUGINS:  # explicit registration wins
            register_plugin(cls)


# --------------------------------------------------------------------------- #
# built-in plugins
# --------------------------------------------------------------------------- #


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 0

    def activate(self, context, state: ActivationState) -> None:
        for k, v in (context or {}).items():
            state.set_env(k, str(v))


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 10

    def pack(self, value, runtime):
        return _upload_dir(value, runtime) if isinstance(value, str) \
            else value

    def create(self, value, runtime):
        return _materialize(value, runtime) if isinstance(value, dict) \
            else None

    def activate(self, path, state: ActivationState) -> None:
        if path:
            state.chdir(path)
            state.add_sys_path(path)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 20

    def pack(self, value, runtime):
        return [_upload_dir(m, runtime) if isinstance(m, str) else m
                for m in (value or [])]

    def create(self, value, runtime):
        return [_materialize(m, runtime) for m in (value or [])
                if isinstance(m, dict)]

    def activate(self, paths, state: ActivationState) -> None:
        for p in paths or ():
            state.add_sys_path(p)


class PipPlugin(RuntimeEnvPlugin):
    """Per-requirement-set virtualenv (reference: runtime_env/pip.py —
    per-env-hash venv with delete-on-failure + cross-process locking)."""

    name = "pip"
    priority = 30

    def create(self, pip_spec, runtime) -> Optional[str]:
        import fcntl
        import subprocess

        if not pip_spec:
            return None
        if isinstance(pip_spec, dict):
            reqs = list(pip_spec.get("packages") or [])
            opts = list(pip_spec.get("pip_install_options") or [])
        else:
            reqs = list(pip_spec)
            opts = []
        digest = hashlib.blake2b(
            ("\n".join(sorted(reqs) + sorted(opts))).encode(),
            digest_size=12).hexdigest()
        os.makedirs(_CACHE_ROOT, exist_ok=True)
        dest = os.path.join(_CACHE_ROOT, f"pip-{digest}")
        marker = dest + ".ok"

        def site_packages() -> str:
            v = f"python{sys.version_info.major}.{sys.version_info.minor}"
            return os.path.join(dest, "lib", v, "site-packages")

        if os.path.exists(marker):
            return site_packages()
        with open(dest + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(marker):
                return site_packages()
            import shutil
            import venv

            shutil.rmtree(dest, ignore_errors=True)  # prior failed attempt
            try:
                venv.create(dest, system_site_packages=True, with_pip=True,
                            symlinks=True)
                # when THIS interpreter itself lives in a venv (/opt/venv),
                # system_site_packages points past it to the base python —
                # bridge our site-packages in via a .pth so pip's build
                # backend (setuptools) and the framework stay importable
                host_sps = [p for p in sys.path
                            if p.endswith("site-packages")
                            and os.path.isdir(p)]
                if host_sps:
                    with open(os.path.join(site_packages(),
                                           "_raytpu_host.pth"), "w") as f:
                        f.write("\n".join(host_sps) + "\n")
                pip = os.path.join(dest, "bin", "pip")
                proc = subprocess.run(
                    [pip, "install", "--disable-pip-version-check",
                     "--no-input"] + opts + reqs,
                    capture_output=True, text=True, timeout=600)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install failed for runtime_env {reqs}:\n"
                        + proc.stderr[-2000:])
                open(marker, "w").close()
            except BaseException:
                shutil.rmtree(dest, ignore_errors=True)
                raise
        return site_packages()

    def activate(self, sp, state: ActivationState) -> None:
        if sp:
            state.add_sys_path(sp)


class CondaPlugin(RuntimeEnvPlugin):
    """Conda envs (reference: runtime_env/conda.py). Value forms:
    an ``environment.yml`` path, a dict spec (JSON is valid YAML, so
    dicts serialize directly), or the name of a pre-existing env.

    Materialization shells out to the host's ``conda`` — on hosts
    without one (like this zero-egress image) ``create`` raises an
    honest RuntimeError instead of pretending. Because workers share
    the interpreter, activation prepends the env's ``bin`` to PATH and
    bridges its site-packages ONLY when the env's python matches the
    running interpreter's major.minor."""

    name = "conda"
    priority = 40

    def pack(self, value, runtime):
        if isinstance(value, str) and (os.sep in value
                                       or os.path.isfile(value)):
            with open(value) as f:
                return {"yaml": f.read()}
        if isinstance(value, dict) and "yaml" not in value:
            # a dict env spec: JSON-serialize (YAML superset) for hashing
            return {"yaml": json.dumps(value, sort_keys=True)}
        return value  # named env or already-packed

    def _conda_exe(self) -> str:
        import shutil

        exe = os.environ.get("CONDA_EXE") or shutil.which("conda")
        if not exe:
            raise RuntimeError(
                "runtime_env 'conda' requires a conda executable on the "
                "worker host (none found in PATH or CONDA_EXE); this "
                "image has no conda — use 'pip' envs instead")
        return exe

    def create(self, value, runtime):
        import fcntl
        import subprocess

        if isinstance(value, str):  # pre-existing named env
            exe = self._conda_exe()
            out = subprocess.run([exe, "env", "list", "--json"],
                                 capture_output=True, text=True, timeout=60)
            for prefix in json.loads(out.stdout or "{}").get("envs", []):
                if os.path.basename(prefix) == value:
                    return {"prefix": prefix}
            raise RuntimeError(f"conda env {value!r} not found")
        yaml_text = value["yaml"]
        digest = hashlib.blake2b(yaml_text.encode(),
                                 digest_size=12).hexdigest()
        os.makedirs(_CACHE_ROOT, exist_ok=True)
        dest = os.path.join(_CACHE_ROOT, f"conda-{digest}")
        marker = dest + ".ok"
        if os.path.exists(marker):
            return {"prefix": dest}
        exe = self._conda_exe()  # fail fast before taking the lock
        with open(dest + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(marker):
                return {"prefix": dest}
            import shutil
            import tempfile

            shutil.rmtree(dest, ignore_errors=True)
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".yml", delete=False) as f:
                f.write(yaml_text)
                spec_path = f.name
            try:
                proc = subprocess.run(
                    [exe, "env", "create", "-p", dest, "-f", spec_path],
                    capture_output=True, text=True, timeout=1800)
                if proc.returncode != 0:
                    raise RuntimeError(
                        "conda env create failed:\n" + proc.stderr[-2000:])
                open(marker, "w").close()
            except BaseException:
                shutil.rmtree(dest, ignore_errors=True)
                raise
            finally:
                os.unlink(spec_path)
        return {"prefix": dest}

    def activate(self, context, state: ActivationState) -> None:
        prefix = context["prefix"]
        state.set_env("PATH", os.path.join(prefix, "bin") + os.pathsep
                      + os.environ.get("PATH", ""))
        state.set_env("CONDA_PREFIX", prefix)
        v = f"python{sys.version_info.major}.{sys.version_info.minor}"
        sp = os.path.join(prefix, "lib", v, "site-packages")
        if os.path.isdir(sp):
            state.add_sys_path(sp)


# --------------------------------------------------------------------------- #
# runtime entry points (same surface as before the plugin refactor)
# --------------------------------------------------------------------------- #


def pack_runtime_env(env: Optional[dict], runtime) -> Optional[dict]:
    """Driver/submitter side: run every key's plugin ``pack`` hook."""
    if not env:
        return env
    _ensure_plugins()
    out = {}
    for key, value in env.items():
        plugin = _PLUGINS.get(key)
        if plugin is None:
            raise ValueError(
                f"unknown runtime_env key {key!r} (no plugin registered; "
                f"known: {sorted(_PLUGINS)})")
        out[key] = plugin.pack(value, runtime)
    return out


def apply_runtime_env(env: Optional[dict], runtime):
    """Worker side: create+activate each key's plugin (priority order);
    returns a restore() callable (no-op when nothing was applied)."""
    if not env:
        return lambda: None
    _ensure_plugins()
    state = ActivationState()
    try:
        for plugin in sorted(
                (p for k, p in _PLUGINS.items()
                 if k in env and env[k] is not None),
                key=lambda p: p.priority):
            context = plugin.create(env[plugin.name], runtime)
            plugin.activate(context, state)
    except BaseException:
        state.restore()  # partial application must not leak into later tasks
        raise
    return state.restore
