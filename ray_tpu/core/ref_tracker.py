"""Per-process ObjectRef accounting: who created each ref, how big, where.

Analog of the reference's owner-side reference table
(``src/ray/core_worker/reference_count.h`` — per-ref creator callsite,
size, local/borrow counts) that backs ``ray memory`` /
``memory_summary()``. Every process (driver, worker) keeps one table:

- ``incref``/``decref`` track live ObjectRef handles (wired through the
  runtimes' ``add_local_ref``/``remove_local_ref``),
- ``annotate`` stamps creation metadata at the points refs are minted
  (put / task return / actor return / stream item): kind, payload size
  when known, creator task/actor name, creation time, and — gated by
  ``RAY_TPU_RECORD_REF_CREATION_SITES`` — the user callsite
  (``file:line:function``, first frame outside the ray_tpu package),
- ``note_borrow`` marks deserialized refs (handles this process holds
  but does not own — the reference's borrower bookkeeping),
- ``export`` snapshots live entries; workers ship it to the head over
  the metrics-report cadence (one-way ``refs`` message), where it joins
  the object directory into the cluster ownership table
  (``Head.memory_table``).

Cost discipline: ``RAY_TPU_REF_ACCOUNTING_ENABLED=0`` turns the whole
table off (every hook is a cached-flag check + return); with accounting
on but callsites off, a hook is one dict operation under a lock — the
``bench_objects.py --check`` gate holds put/get p50 regression to <= 3%
with callsites off and <= 10% with them on.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional, Tuple

KIND_PUT = "put"
KIND_TASK_RETURN = "task_return"
KIND_ACTOR_RETURN = "actor_return"
KIND_STREAM_ITEM = "stream_item"
KIND_BORROW = "borrow"

# entry layout: [count, kind, size, callsite, creator, created_at]
_COUNT, _KIND, _SIZE, _SITE, _CREATOR, _CREATED = range(6)

_lock = threading.Lock()
_entries: Dict[object, list] = {}
_dirty = False
# (accounting_enabled, record_creation_sites); None until first use so the
# config snapshot shipped to workers is honored (refresh_flags for tests)
_flags: Optional[Tuple[bool, bool]] = None

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_flags() -> Tuple[bool, bool]:
    global _flags
    try:
        from .config import global_config

        cfg = global_config()
        _flags = (bool(cfg.ref_accounting_enabled),
                  bool(cfg.record_ref_creation_sites))
    except Exception:
        _flags = (True, False)
    return _flags


def refresh_flags() -> None:
    """Re-read the config gates on next use (tests toggle them live)."""
    global _flags
    _flags = None


def enabled() -> bool:
    f = _flags
    return (f or _load_flags())[0]


def recording_sites() -> bool:
    f = _flags
    return (f or _load_flags())[1]


def _callsite() -> str:
    """First frame outside the ray_tpu package: file:line:function."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover
        return "<unknown>"
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR):
            return f"{fn}:{f.f_lineno}:{f.f_code.co_name}"
        f = f.f_back
    return "<internal>"


def incref(oid) -> None:
    """A live ObjectRef handle appeared in this process."""
    f = _flags
    if not (f or _load_flags())[0]:
        return
    global _dirty
    with _lock:
        e = _entries.get(oid)
        if e is None:
            _entries[oid] = [1, None, None, None, None, time.time()]
        else:
            e[_COUNT] += 1
        _dirty = True


def decref(oid) -> None:
    """A handle died (ObjectRef.__del__ via the runtime's ref drop)."""
    f = _flags
    if not (f or _load_flags())[0]:
        return
    global _dirty
    with _lock:
        e = _entries.get(oid)
        if e is None:
            return
        e[_COUNT] -= 1
        if e[_COUNT] <= 0:
            del _entries[oid]
        _dirty = True


def annotate(oid, kind: str, size: Optional[int] = None,
             creator: Optional[str] = None,
             callsite: Optional[str] = None) -> None:
    """Stamp creation metadata on one ref (first annotation wins)."""
    f = _flags
    if not (f or _load_flags())[0]:
        return
    if callsite is None and (f or _flags)[1]:
        callsite = _callsite()
    global _dirty
    with _lock:
        e = _entries.get(oid)
        if e is None:
            e = _entries[oid] = [0, None, None, None, None, time.time()]
        if e[_KIND] is None or e[_KIND] == KIND_BORROW:
            e[_KIND] = kind
            if callsite is not None:
                e[_SITE] = callsite
            if creator is not None:
                e[_CREATOR] = creator
        if e[_SIZE] is None and size is not None:
            e[_SIZE] = int(size)
        _dirty = True


def annotate_many(oids, kind: str, creator: Optional[str] = None) -> None:
    """Annotate several refs minted at one callsite (task returns):
    the frame walk happens once for the whole batch."""
    f = _flags
    if not (f or _load_flags())[0]:
        return
    site = _callsite() if (f or _flags)[1] else None
    for oid in oids:
        annotate(oid, kind, creator=creator, callsite=site)


def note_borrow(oid) -> None:
    """A ref was deserialized here: this process borrows, not owns."""
    f = _flags
    if not (f or _load_flags())[0]:
        return
    global _dirty
    with _lock:
        e = _entries.get(oid)
        if e is None:
            e = _entries[oid] = [0, None, None, None, None, time.time()]
        if e[_KIND] is None:
            e[_KIND] = KIND_BORROW
        _dirty = True


def lookup(oid) -> Optional[tuple]:
    """(count, kind, size, callsite, creator, created_at) or None —
    the store's high-watermark event uses this to name top consumers."""
    with _lock:
        e = _entries.get(oid)
        return tuple(e) if e is not None else None


def export() -> Dict[object, tuple]:
    """Snapshot of live entries: {oid: (count, kind, size, callsite,
    creator, created_at)}. Full-state (not a delta): the head overwrites
    per source, so dropped refs vanish on the next report."""
    with _lock:
        return {oid: tuple(e) for oid, e in _entries.items()
                if e[_COUNT] > 0}


def live_count(oid) -> int:
    with _lock:
        e = _entries.get(oid)
        return e[_COUNT] if e is not None else 0


def reset() -> None:
    """Drop every entry (cluster shutdown / test isolation)."""
    global _dirty
    with _lock:
        _entries.clear()
        _dirty = True


def start_report(send_fn, interval_s: float) -> threading.Event:
    """Worker-side: periodically ship the export via ``send_fn`` (the
    one-way ``refs`` channel message), mirroring the metrics report
    thread. Sends only when the table changed; a failed send re-marks
    dirty so the next tick retries."""
    stop = threading.Event()

    def loop():
        global _dirty
        while not stop.wait(max(0.05, interval_s)):
            if not enabled():
                continue
            with _lock:
                if not _dirty:
                    continue
                _dirty = False
                snap = {oid: tuple(e) for oid, e in _entries.items()
                        if e[_COUNT] > 0}
            try:
                send_fn(snap)
            except Exception:
                with _lock:
                    _dirty = True

    threading.Thread(target=loop, daemon=True, name="ref-report").start()
    return stop
