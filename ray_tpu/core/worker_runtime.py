"""Worker process runtime + executor.

Analog of the reference's CoreWorker in WORKER mode plus the Python worker
shell (``python/ray/_private/workers/default_worker.py`` +
``core_worker/transport/task_receiver.cc``): connects to its node over a unix
socket, registers, then serves ``exec`` messages. Holds actor instances,
enforces actor ordering / max_concurrency / asyncio execution (reference:
actor_scheduling_queue.cc, concurrency groups), performs ``get``/``put``
against the node store (zero-copy arena reads), and forwards nested task
submissions to the head (workers are full API clients — reference: workers own
submitted tasks; here the head tracks ownership for them).
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import os
import pickle
import sys
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from . import fault_injection
from . import object_ref as object_ref_mod
from . import ref_tracker, serialization
from .config import Config, set_global_config, global_config
from .exceptions import ObjectLostError, TaskCancelledError, TaskError, GetTimeoutError
from .ids import ActorID, JobID, ObjectID, TaskID
from .object_ref import ObjectRef
from .object_store import ArenaClient
from .protocol import Channel, RpcClient, connect
from .task_spec import TaskSpec


from ray_tpu.experimental.channel import is_arraylike as _is_arraylike
from ray_tpu.util import flight_recorder as _fr

_sp_dag_exec = _fr.register_span("dag.exec", tag_keys=("method",))
_sp_batch_drain = _fr.register_span("dag.batch_drain", tag_keys=("method",))


class _BatchErrPayload:
    """Pre-serialized TAG_ERROR payload standing in a batch result slot
    (the whole batch call failed: every item ships the same error)."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes):
        self.payload = payload


class _ActorState:
    def __init__(self, instance, max_concurrency: int, is_async: bool):
        self.instance = instance
        self.is_async = is_async
        if is_async:
            self.loop = asyncio.new_event_loop()
            self.loop_thread = threading.Thread(
                target=self.loop.run_forever, daemon=True, name="actor-asyncio"
            )
            self.loop_thread.start()
            self.pool = ThreadPoolExecutor(max_workers=1)  # for sync methods
        else:
            self.loop = None
            self.pool = ThreadPoolExecutor(max_workers=max_concurrency)
        # serial actors (sync, max_concurrency=1): compiled-graph executor
        # loops call the method DIRECTLY under this lock instead of paying
        # the ~100us pool submit/result thread handoff per hop; eager
        # method bodies take the same lock on their pool thread, so the
        # one-method-at-a-time actor contract holds across both planes
        self.exec_lock = (threading.Lock()
                          if not is_async and max_concurrency == 1 else None)
        # compiled-exec scheduling: tokens from higher-priority loops
        # holding the actor (1F1B backward-over-forward); deque for
        # thread-safe append/pop, condition for the low-priority loops
        # to park on instead of polling while a backward runs
        self.prio_waiting: deque = deque()
        self.prio_cv = threading.Condition()

    def stop(self) -> None:
        """Release the actor's execution machinery (worker exit path;
        os._exit would reap the threads anyway, but pending work gets a
        chance to settle and the lifecycle is explicit)."""
        self.pool.shutdown(wait=False)
        if self.loop is not None:
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
            except RuntimeError:
                pass  # loop already closed
            self.loop_thread.join(timeout=1.0)


class WorkerRuntime:
    """Runtime installed as the process-global API backend inside workers."""

    def __init__(self, channel: Channel, init_info: dict):
        self.channel = channel
        self.rpc = RpcClient(channel)
        self.worker_id: bytes = init_info["worker_id"]
        self.node_hex: str = init_info["node_hex"]
        self.node_ip: str = init_info.get("node_ip", "127.0.0.1")
        self.job_id = JobID(init_info["job_id"])
        # the node's session dir: workers hosting serve replicas write
        # their access logs under <session_dir>/logs/serve/
        self.session_dir: str = init_info.get("session_dir", "")
        set_global_config(Config.from_json(init_info["config"]))
        _fr.adopt_config(global_config())
        _fr.set_process_label(f"worker:{os.getpid()}")
        if self.session_dir:
            _fr.set_dump_dir(self.session_dir)
        # adopt the node's extra import roots (driver-side sys.path inserts)
        # so by-reference pickles of driver-loaded modules resolve here
        for p in init_info.get("sys_path", []):
            if p not in sys.path:
                sys.path.append(p)
        self.arena = ArenaClient(init_info["arena_path"], init_info["arena_capacity"])
        self._fn_cache: Dict[str, Any] = {}
        self._actors: Dict[ActorID, _ActorState] = {}
        # ONE thread: plain tasks execute strictly one-at-a-time per worker
        # process (the ray semantic user code relies on for process-global
        # state, e.g. jax). Staged (pipelined) tasks queue behind the
        # running one and can be handed back via "unstage".
        self._task_pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="exec")
        self._staged: Dict[object, Any] = {}  # task_id -> pending Future
        self._put_counter = 0
        self._put_lock = threading.Lock()
        self._current_task = threading.local()
        self._cancelled: set = set()
        self._shutdown = threading.Event()
        self.accelerator_binding: Dict[str, List[int]] = {}
        # direct (head-bypass) path: this worker OWNS its eligible nested
        # submissions (reference: submitter-side TaskManager + memory
        # store). Arg pins are owner-side (the manager's pin table) plus
        # holder leases the executing node takes from spec.pinned_args —
        # no pin traffic leaves this process.
        from .direct import DirectTaskManager

        self.direct = DirectTaskManager(
            self._direct_submit,
            ext_wait=self._ext_wait_objects)
        # direct actor calls (resolve runs on the submitter's own resolver
        # thread, so a blocking RPC there is safe)
        from .direct import DirectActorSubmitter

        self.direct_actors = DirectActorSubmitter(
            self.direct, self._direct_submit,
            lambda aid: self.rpc.call("rpc", "actor_location", aid))

    def _ext_wait_objects(self, oids, timeout):
        """One availability round against the cluster object directory
        (dependency resolver's external-object wait)."""
        return self.rpc.call("store", "wait", list(oids), len(oids),
                             timeout, timeout=None)

    # ------------------------------------------------------------------ API
    # (same surface the driver runtime exposes; public api dispatches here)

    def is_initialized(self) -> bool:
        return True

    @property
    def mode(self) -> str:
        return "WORKER"

    def put(self, value: Any, _owner=None) -> ObjectRef:
        with self._put_lock:
            self._put_counter += 1
            idx = self._put_counter
        tid = getattr(self._current_task, "task_id", None)
        if tid is not None:
            oid = ObjectID.for_put(tid, idx)
        else:
            oid = ObjectID.from_random()  # put outside a task context
        sobj = serialization.serialize(value)
        self._store_object(oid, sobj, is_error=False)
        self.rpc.call("rpc", "register_owned_object", oid)
        ref = ObjectRef(oid)
        ref_tracker.annotate(
            oid, ref_tracker.KIND_PUT, size=sobj.total_bytes,
            creator=getattr(self._current_task, "name", None) or "worker")
        return ref

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            # owner_node doubles as a location hint (stream items carry
            # the executor node so the pull goes peer-to-peer)
            hint = r.owner_node if isinstance(r.owner_node, str) else None
            out.append(self._get_one(r.id, remaining, hint))
        return out

    def _get_one(self, oid: ObjectID, timeout: Optional[float],
                 hint: Optional[str] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        # owned direct results resolve in-process (blocks until the
        # executor's reply lands; no node round-trip)
        local = self.direct.get_local(oid, timeout)
        owned_store = False
        if local is not None:
            payload, is_error = local
            if payload is not None:
                value = serialization.deserialize(payload)
                if is_error:
                    raise value
                return value
            # large result: sealed in a node store — fall through, with
            # the sealing node as a pull hint
            owned_store = self.direct.owns_lineage(oid)
            hint = hint or self.direct.result_node(oid)
        if owned_store:
            # bounded first round: if the sealing node died, this owner is
            # the only process that can resubmit the creating task (owner
            # lineage — reference object_recovery_manager.h:90). The 2 s
            # grace absorbs location-report lag before declaring loss.
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            probe_t = 2.0 if remaining is None else min(remaining, 2.0)
            rep = self.rpc.call("store", "get", oid, probe_t, hint,
                                timeout=None)
            if rep[0] == "timeout":
                located = self.rpc.call("store", "wait", [oid], 1, 0.0,
                                        timeout=None)
                if not located and self.direct.recover(oid):
                    remaining = (None if deadline is None
                                 else max(0.0, deadline - time.monotonic()))
                    return self._get_one(oid, remaining)
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                rep = self.rpc.call("store", "get", oid, remaining, hint,
                                    timeout=None)
        else:
            rep = self.rpc.call("store", "get", oid, timeout, hint,
                                timeout=None)
        kind = rep[0]
        if kind == "timeout":
            raise GetTimeoutError(f"get timed out on {oid.hex()}")
        if kind == "inline":
            _, payload, is_error = rep
            value = serialization.deserialize(payload)
        else:
            _, offset, size, is_error = rep
            view = self.arena.view(offset, size)
            value = serialization.deserialize(view)
        if is_error:
            raise value
        return value

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        oids = [r.id for r in refs]
        owned_pending = self.direct.pending_oids(oids)
        if not owned_pending:
            ready_set = set(self.direct.ready_subset(oids))
            rest = [o for o in oids if o not in ready_set]
            if rest and len(ready_set) < num_returns:
                ready_set |= set(self.rpc.call(
                    "store", "wait", rest,
                    num_returns - len(ready_set), timeout, fetch_local,
                    timeout=None))
        else:
            # some requested oids are still-running direct tasks this
            # worker owns: event-driven rounds over both sources (direct
            # completions set the event; cluster seals covered by the
            # bounded head round)
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            ev = threading.Event()
            self.direct.add_waiter(ev)
            try:
                while True:
                    ready_set = set(self.direct.ready_subset(oids))
                    pending = self.direct.pending_oids(oids)
                    rest = [o for o in oids if o not in ready_set
                            and o not in pending]
                    if rest and len(ready_set) < num_returns:
                        ready_set |= set(self.rpc.call(
                            "store", "wait", rest,
                            num_returns - len(ready_set), 0.0, fetch_local,
                            timeout=None))
                    if len(ready_set) >= num_returns:
                        break
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        break
                    ev.wait(0.2 if remaining is None
                            else min(0.2, remaining))
                    ev.clear()
            finally:
                self.direct.remove_waiter(ev)
        ready = [r for r in refs if r.id in ready_set][:num_returns]
        chosen = {r.id for r in ready}
        not_ready = [r for r in refs if r.id not in chosen]
        return ready, not_ready

    def _direct_submit(self, spec: TaskSpec) -> None:
        self.channel.send("dsubmit", pickle.dumps(spec))

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        from .direct import direct_eligible

        if global_config().direct_task_enabled and direct_eligible(spec):
            spec.owner_is_driver = False
            ready = self.direct.register(spec)
            if ready is not None:  # else: dep resolver submits it later
                self._direct_submit(ready)
        else:
            self.rpc.call("rpc", "submit_task", pickle.dumps(spec))
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        ref_tracker.annotate_many(
            spec.return_ids(),
            ref_tracker.KIND_ACTOR_RETURN if spec.actor_id is not None
            else ref_tracker.KIND_TASK_RETURN,
            creator=spec.function_name)
        return refs

    def register_function(self, function_id: str, payload: bytes) -> None:
        self.rpc.call("rpc", "register_function", function_id, payload)

    def get_function(self, function_id: str):
        if function_id not in self._fn_cache:
            payload = self.rpc.call("rpc", "get_function", function_id)
            if payload is None:
                raise RuntimeError(f"function {function_id} not found in GCS")
            self._fn_cache[function_id] = pickle.loads(payload)
        return self._fn_cache[function_id]

    def get_actor_info(self, name: str, namespace: str):
        return self.rpc.call("rpc", "get_named_actor", name, namespace)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.rpc.call("rpc", "kill_actor", actor_id, no_restart)

    def cancel_task(self, oid: ObjectID, force: bool = False):
        if self.direct.cancel(oid):
            # owner-side mark + node-side dequeue/interrupt
            self.channel.send("dcancel", oid.task_id(), force)
            return
        self.rpc.call("rpc", "cancel_task", oid, force)

    def kv(self, op: str, *args):
        return self.rpc.call("rpc", "kv", op, *args)

    def object_locations(self, oids: List[ObjectID]) -> List[List[str]]:
        """Per-object holder node hexes (head directory + owned results)."""
        out = self.rpc.call("rpc", "object_locations", list(oids))
        self.direct.fill_result_locations(oids, out)
        return out

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    # reference counting: workers batch releases to the owner (head);
    # the local ref tracker still counts live handles so this process's
    # local/borrow table exports to the cluster memory view
    def add_local_ref(self, oid: ObjectID) -> None:
        ref_tracker.incref(oid)

    def remove_local_ref(self, oid: ObjectID) -> None:
        ref_tracker.decref(oid)
        self.direct.drop(oid)

    def add_borrow_ref(self, oid: ObjectID) -> None:
        pass

    def runtime_context(self) -> dict:
        tid = getattr(self._current_task, "task_id", None)
        aid = getattr(self._current_task, "actor_id", None)
        return {
            "job_id": self.job_id,
            "node_id": self.node_hex,
            "node_ip": self.node_ip,
            "worker_id": self.worker_id,
            "task_id": tid,
            "actor_id": aid,
            "accelerator_ids": dict(self.accelerator_binding),
            "mode": "WORKER",
        }

    def available_resources(self):
        return self.rpc.call("rpc", "available_resources")

    def cluster_resources(self):
        return self.rpc.call("rpc", "cluster_resources")

    def nodes(self):
        return self.rpc.call("rpc", "nodes")

    def actor_method_call(self, spec: TaskSpec) -> List[ObjectRef]:
        cfg = global_config()
        if (cfg.direct_task_enabled and cfg.direct_actor_enabled
                and self.direct_actors.try_submit(spec)):
            refs = [ObjectRef(oid) for oid in spec.return_ids()]
            ref_tracker.annotate_many(spec.return_ids(),
                                      ref_tracker.KIND_ACTOR_RETURN,
                                      creator=spec.function_name)
            return refs
        # direct path disabled by config (a whole-session toggle, so
        # every call to every actor takes the same path and per-caller
        # ordering is structural): head path
        return self.submit_task(spec)

    def create_placement_group(self, bundles, strategy, name=""):
        return self.rpc.call("rpc", "create_placement_group", bundles, strategy, name)

    def placement_group_op(self, op, *args):
        return self.rpc.call("rpc", "pg_" + op, *args)

    # --------------------------------------------------------------- storage

    def _store_object(self, oid: ObjectID, sobj: serialization.SerializedObject,
                      is_error: bool) -> None:
        cfg = global_config()
        size = sobj.total_bytes
        if size <= cfg.max_direct_call_object_size:
            self.rpc.call("store", "put_inline", oid, sobj.to_bytes(), is_error)
        else:
            offset = self.rpc.call("store", "create", oid, size)
            view = self.arena.view(offset, size)
            # writev-style: source buffers pack straight into shared memory
            sobj.write_into_view(view)
            self.rpc.call("store", "seal", oid, is_error)

    # --------------------------------------------------------------- serve

    def serve_forever(self) -> None:
        try:
            while not self._shutdown.is_set():
                try:
                    tag, payload = self.channel.recv()
                except (EOFError, OSError):
                    break
                if tag == "rep":
                    self.rpc.handle_reply(*payload)
                elif tag == "ddone":
                    # direct-task completion (may resubmit a retry inline)
                    task_id, err_name, results, exec_hex = payload
                    self.direct.complete(task_id, err_name, results,
                                         exec_hex)
                elif tag == "dstream":
                    # stream-item announcement for a direct task this
                    # worker owns (FIFO with its ddone on this channel)
                    task_id, index, data, exec_hex = payload
                    self.direct.on_stream_item(task_id, index, data,
                                               exec_hex)
                elif tag == "ssub":
                    # a remote consumer subscribed to a stream this worker
                    # owns. Steady state (item already buffered) answers
                    # INLINE — a zero-timeout probe off the reader thread
                    # costs one lock hop; only a round that would PARK
                    # (next item not produced yet) gets its own thread.
                    req_id, task_id, index, sub_t = payload
                    try:
                        rep = self.direct.stream_next_remote(
                            task_id, index, 0)
                    except Exception:
                        rep = None
                    if rep is not None and rep[0] != "wait":
                        self.channel.send("srep", req_id, rep)
                    elif rep is None:
                        self.channel.send(
                            "srep", req_id,
                            ("gone", "not the stream owner"))
                    else:
                        threading.Thread(
                            target=self._serve_stream_sub, args=payload,
                            daemon=True, name="ssub").start()
                elif tag == "exec":
                    spec: TaskSpec = pickle.loads(payload[0])
                    binding = payload[1]
                    self._dispatch_exec(spec, binding)
                elif tag == "cancel":
                    self._cancelled.add(payload[0])
                elif tag == "stack":
                    # cluster stack dump: sampling blocks for the dump
                    # duration, so it runs off the reader thread and
                    # replies one-way (the node's collector has a
                    # deadline; a dead worker's slot is failed there)
                    threading.Thread(
                        target=self._reply_stacks, args=payload,
                        daemon=True, name="stack-dump").start()
                elif tag == "node_ip":
                    # node learned its routable IP after this worker
                    # registered (head-node prestart race)
                    self.node_ip = payload[0]
                elif tag == "unstage":
                    # node reclaims a staged-but-unstarted task (another
                    # worker went idle); only possible pre-execution, so
                    # requeueing it elsewhere never duplicates side effects
                    tid = payload[0]
                    fut = self._staged.get(tid)
                    if fut is not None and fut.cancel():
                        self._staged.pop(tid, None)
                        self.channel.send("unstaged", tid)
                elif tag == "shutdown":
                    break
        finally:
            self._shutdown.set()
            # explicit resource teardown (os._exit skips everything):
            # actor pools/loops first, then the shared task pool
            for st in list(self._actors.values()):
                try:
                    st.stop()
                except Exception:
                    pass
            self._task_pool.shutdown(wait=False)
            dump = getattr(self, "_profile_dump", None)
            if dump is not None:
                dump()  # os._exit skips atexit
            # buffered observability (span batches, deferred serve
            # bookkeeping) flushes from daemon threads that os._exit
            # kills — drain what's queued so a replica's final requests
            # keep their spans and access-log lines. Only if the modules
            # are already loaded; never import on the exit path.
            try:
                tr = sys.modules.get("ray_tpu.util.tracing")
                if tr is not None:
                    tr._flush_spans()
                so = sys.modules.get("ray_tpu.serve.observability")
                if so is not None:
                    so.flush_all()
                # final flight-recorder drain: the periodic span report
                # thread dies with os._exit, so push the tail now
                pl = _fr.drain()
                if pl is not None:
                    self.channel.send("spans", pl)
            except Exception:
                pass
            os._exit(0)

    def _dispatch_exec(self, spec: TaskSpec, binding: Dict[str, List[int]]) -> None:
        if spec.actor_id is not None and not spec.is_actor_creation:
            st = self._actors.get(spec.actor_id)
            if st is None:
                self._send_error(spec, RuntimeError("actor instance not found"))
                return
            fn_name = spec.function_name.rsplit(".", 1)[-1]
            method = getattr(type(st.instance), fn_name, None)
            if st.is_async and method is not None and asyncio.iscoroutinefunction(method):
                fut = asyncio.run_coroutine_threadsafe(
                    self._execute_async(spec, st), st.loop
                )
                fut.add_done_callback(lambda f: f.exception())
            else:
                st.pool.submit(self._execute, spec, binding)
        else:
            fut = self._task_pool.submit(self._execute, spec, binding)
            self._staged[spec.task_id] = fut
            fut.add_done_callback(
                lambda _f, tid=spec.task_id: self._staged.pop(tid, None))

    async def _execute_async(self, spec: TaskSpec, st: _ActorState) -> None:
        span_cm = None
        try:
            if spec.task_id in self._cancelled:
                raise TaskCancelledError(f"task {spec.task_id.hex()} cancelled")
            if spec.trace_ctx is not None:
                from ray_tpu.util.tracing import task_span

                span_cm = task_span(spec)
                if span_cm is not None:
                    span_cm.__enter__()
            args, kwargs = self._resolve_args(spec)
            fn_name = spec.function_name.rsplit(".", 1)[-1]
            method = getattr(st.instance, fn_name)
            self._current_task.task_id = spec.task_id
            self._current_task.actor_id = spec.actor_id
            self._current_task.name = spec.function_name
            result = await method(*args, **kwargs)
            self._finish(spec, result)
        except Exception as e:  # noqa: BLE001
            self._send_error(spec, e)
        finally:
            if span_cm is not None:
                span_cm.__exit__(None, None, None)
            self._current_task.task_id = None
            self._current_task.actor_id = None
            self._current_task.name = None

    def _compiled_setup(self, desc: dict) -> dict:
        """Phase A of a cross-node compiled-graph install: create the
        NetRing reader endpoints this process owns (the READING side
        holds the receive ring) and return the dial-in for this
        process's ring host so producing processes can connect."""
        from ray_tpu.core import net_ring

        for spec in desc.get("rings", ()):
            net_ring.create_reader(spec["ring"], spec["n_slots"],
                                   spec["capacity"],
                                   advertise_ip=self.node_ip)
        host = net_ring.ensure_host(self.node_ip)
        return {"addr": list(host.address), "key": host.authkey.hex()}

    def _open_compiled_chan(self, d, capacity: int):
        """Open one compiled-graph edge from its descriptor: a /dev/shm
        ring path, a locally-created net reader (Phase A), or a net
        writer dialing a remote ring host."""
        from ray_tpu.core import net_ring
        from ray_tpu.experimental.channel import ShmChannel

        if isinstance(d, str):
            return ShmChannel(d, capacity)
        kind = d[0]
        if kind == "shm":
            return ShmChannel(d[1], capacity)
        if kind == "netr":
            reader = net_ring.ensure_host(self.node_ip).get(d[1])
            if reader is None:
                raise RuntimeError(
                    f"net ring {d[1]} was not set up in this process")
            return reader
        if kind == "netw":
            _, host, port, key, ring_id, n_slots = d
            return net_ring.NetRingWriter.connect(
                (host, port), bytes.fromhex(key), ring_id, n_slots,
                capacity)
        raise ValueError(f"unknown channel descriptor {d!r}")

    def _start_compiled_exec(self, st: _ActorState, desc: dict) -> None:
        ins = [self._open_compiled_chan(p, desc["capacity"])
               for p in desc["in_paths"]]
        outs = [self._open_compiled_chan(p, desc["capacity"])
                for p in desc["out_paths"]]
        method = getattr(st.instance, desc["method"])
        template = list(desc.get("args_template") or [("edge", 0)])
        device = bool(desc.get("device"))
        priority = int(desc.get("priority") or 0)
        batch_max = int(desc.get("batch_max") or 0)
        direct_call = bool(desc.get("direct_call"))
        stream_replies = bool(desc.get("stream_replies"))
        # backlog visibility hook (serve replicas): the instance can see
        # its own in-edge occupancy, so queued-in-ring requests count in
        # load signals (autoscaling) the same way eager in-flight does
        hook = getattr(st.instance, "__compiled_channels_hook__", None)
        if hook is not None:
            try:
                hook(desc["uid"], ins)
            except Exception:
                hook = None

        from ray_tpu.experimental.channel import TAG_STOP

        def close_all():
            if hook is not None:
                try:
                    hook(desc["uid"], None)
                except Exception:
                    pass
            for ch in ins + outs:
                ch.close()

        def propagate(tag, payload=b""):
            # STOP is best-effort/bounded (teardown); ERROR must NEVER be
            # dropped — a missing message desyncs the downstream join's
            # lockstep rounds forever
            for ch in outs:
                try:
                    ch.write(payload, tag=tag,
                             timeout=10.0 if tag == TAG_STOP else None)
                except Exception:
                    pass

        def loop():
            try:
                self._compiled_exec_loop(ins, outs, propagate, st, method,
                                         template, device, priority,
                                         batch_max, direct_call,
                                         stream_replies)
            finally:
                close_all()

        threading.Thread(target=loop, daemon=True,
                         name=f"compiled-exec-{desc['method']}").start()

    def _compiled_exec_loop(self, ins, outs, propagate, st, method,
                            template, device, priority=0, batch_max=0,
                            direct_call=False, stream_replies=False) -> None:
        from ray_tpu.experimental.channel import (
            TAG_BYTES,
            TAG_ERROR,
            TAG_STOP,
            TAG_TENSOR,
            BatchItemError,
            ChannelClosed,
        )

        method_name = getattr(method, "__name__", "compiled")

        def invoke(args):
            """One method call on the right execution surface. The
            ``dag.exec[.<fn>]`` chaos point fires first (crash = the
            replica-death drill for the compiled serve plane)."""
            fault_injection.fire("dag.exec", method_name)
            _t0 = _fr.now()
            try:
                return _invoke_inner(args)
            finally:
                _sp_dag_exec.end(_t0, method_name)

        def _invoke_inner(args):
            if direct_call:
                # opt-in per node: no pool handoff, no exec lock — the
                # method declares itself safe against the actor's eager
                # plane (serve replicas run sync methods concurrently
                # on the eager plane already)
                return method(*args)
            # run on the actor's executor so compiled executions
            # serialize with eager .remote() calls on the same
            # instance (the single-threaded actor contract);
            # async methods go through the actor's event loop
            if st.is_async and asyncio.iscoroutinefunction(method):
                return asyncio.run_coroutine_threadsafe(
                    method(*args), st.loop).result()
            if st.exec_lock is not None:
                # serial-actor fast path: direct call on this loop's
                # thread, mutually excluded with eager calls. The
                # contract is one-method-at-a-time, NOT
                # one-thread-forever: compiled executions run here,
                # not on the pool thread (reference: do_exec_tasks
                # loops own their thread too).
                # Priority (the 1F1B scheduling rule): when a
                # higher-priority loop on this actor has an input
                # ready (backward microbatch), lower-priority loops
                # (forward) yield the actor to it instead of racing
                # for the lock — backward-over-forward is what keeps
                # the pipeline's activation window at K instead of
                # growing with the microbatch count.
                if priority > 0:
                    st.prio_waiting.append(1)
                    try:
                        with st.exec_lock:
                            return method(*args)
                    finally:
                        st.prio_waiting.pop()
                        with st.prio_cv:
                            st.prio_cv.notify_all()
                # park (never poll) while a backward holds the
                # actor; bounded waits make a missed notify
                # harmless. Advisory ordering: the re-check
                # races a backward arriving right after, which
                # only costs one forward running first.
                while st.prio_waiting:
                    with st.prio_cv:
                        if st.prio_waiting:
                            st.prio_cv.wait(0.05)
                with st.exec_lock:
                    return method(*args)
            return st.pool.submit(method, *args).result()

        def write_value(result):
            if device and _is_arraylike(result):
                for ch in outs:
                    ch.write_array(result)
            elif type(result) is bytes:
                # raw-bytes results skip the serializer both ways
                for ch in outs:
                    ch.write(result, tag=TAG_BYTES)
            else:
                sobj = serialization.serialize(result)
                for ch in outs:
                    ch.write_serialized(sobj)

        def error_payload(exc) -> bytes:
            err = TaskError.from_exception(method_name, exc)
            return serialization.serialize(err).to_bytes()

        # stream-reply mode (with_stream_batching): iteration-level
        # continuous batching with many TAG_STREAM frames per request
        if stream_replies and len(ins) == 1:
            self._compiled_stream_loop(ins[0], outs, propagate, invoke,
                                       error_payload, max(1, batch_max),
                                       device, method_name)
            return

        # batch_max >= 1 means the node DECLARED the list-in/list-out
        # contract (with_batching) — it applies even at window 1
        if batch_max >= 1 and len(ins) == 1:
            self._compiled_batch_loop(ins[0], propagate, invoke,
                                      write_value, error_payload,
                                      batch_max, device, BatchItemError,
                                      method_name)
            return

        while True:
            # one message per in-edge per execution (per-round joins;
            # reference: per-execution index across CompiledTasks). With
            # ring channels up to max_inflight rounds queue per edge, so
            # this loop pipelines against its up/downstream stages.
            edge_vals = []
            failed = None
            for ch in ins:
                try:
                    tag, payload = ch.read(timeout=None, to_device=device)
                except ChannelClosed:
                    propagate(TAG_STOP)
                    return
                except Exception:
                    return  # channel unlinked (teardown race)
                if tag == TAG_ERROR:
                    failed = payload  # upstream error passes through
                elif tag == TAG_TENSOR or tag == TAG_BYTES:
                    edge_vals.append(payload)  # typed/raw: no serializer
                else:
                    edge_vals.append(serialization.deserialize(payload))
            if failed is not None:
                propagate(TAG_ERROR, failed)
                continue
            try:
                args = [edge_vals[t[1]] if t[0] == "edge" else t[1]
                        for t in template]
                write_value(invoke(args))
            except Exception as e:  # noqa: BLE001 — ship to consumer
                propagate(TAG_ERROR, error_payload(e))

    def _compiled_batch_loop(self, ch, propagate, invoke, write_value,
                             error_payload, batch_max, device,
                             BatchItemError, method_name="batch") -> None:
        """Ring-fed batch rounds (serve continuous batching): block for
        the first message, then admit everything ALREADY queued in the
        ring — up to ``batch_max`` — into the same method call. Requests
        that arrive while a batch executes are queued by the ring and
        form the next batch, so under load batches fill with zero added
        wait and when idle a single request runs immediately: the
        admission window replaces the ``max_batch_wait`` timer. One
        reply per item, in order; a BatchItemError result fails one
        item without failing its batch-mates."""
        from ray_tpu.experimental.channel import (
            TAG_BYTES,
            TAG_ERROR,
            TAG_STOP,
            TAG_TENSOR,
            ChannelClosed,
        )

        while True:
            entries = []  # ("val", value) | ("err", payload passthrough)
            stop = False
            _t0 = 0.0  # span starts at the FIRST admitted message: idle
            #            park time before a round is not drain time
            while len(entries) < batch_max:
                if entries:
                    try:
                        if not ch.readable():
                            break  # batch = exactly the queued backlog
                    except Exception:
                        return  # channel closed (teardown race)
                try:
                    tag, payload = ch.read(timeout=None, to_device=device)
                except ChannelClosed:
                    stop = True
                    break
                except Exception:
                    return  # channel unlinked (teardown race)
                if not _t0:
                    _t0 = _fr.now()
                if tag == TAG_ERROR:
                    entries.append(("err", payload))
                elif tag == TAG_TENSOR or tag == TAG_BYTES:
                    entries.append(("val", payload))
                else:
                    entries.append(("val",
                                    serialization.deserialize(payload)))
            vals = [v for kind, v in entries if kind == "val"]
            results = []
            if vals:
                try:
                    results = invoke([vals])
                    if not isinstance(results, (list, tuple)) \
                            or len(results) != len(vals):
                        raise TypeError(
                            f"batch method returned "
                            f"{type(results).__name__} of length "
                            f"{len(results) if isinstance(results, (list, tuple)) else 'n/a'} "
                            f"for {len(vals)} inputs")
                except Exception as e:  # noqa: BLE001 — fail every item
                    pl = error_payload(e)
                    results = [_BatchErrPayload(pl)] * len(vals)
            # replies in arrival order: upstream-error passthroughs keep
            # their slot, values take the next computed result
            vi = 0
            for kind, v in entries:
                if kind == "err":
                    propagate(TAG_ERROR, v)
                    continue
                r = results[vi]
                vi += 1
                if isinstance(r, _BatchErrPayload):
                    propagate(TAG_ERROR, r.payload)
                elif isinstance(r, BatchItemError):
                    propagate(TAG_ERROR, error_payload(r.error))
                else:
                    try:
                        write_value(r)
                    except Exception as e:  # unserializable result etc.
                        propagate(TAG_ERROR, error_payload(e))
            _sp_batch_drain.end(_t0, method_name)
            if stop:
                propagate(TAG_STOP)
                return

    def _compiled_stream_loop(self, ch, outs, propagate, invoke,
                              error_payload, batch_max, device,
                              method_name="stream") -> None:
        """Iteration-level continuous batching (the Orca/vLLM admission
        model): the method owns a RUNNING batch of multi-step requests.
        Each round drains newly-arrived requests from the ring backlog —
        BETWEEN model steps, not at batch boundaries — and calls the
        method once with the new ``(corr, value)`` pairs (possibly none
        while a batch is still decoding). The method returns
        ``(replies, active)``: replies are ``(corr, kind, payload)``
        frames shipped back as TAG_STREAM slots (kind "chunk" | "final"
        | "error" — one request answers with MANY frames over its
        lifetime), and ``active`` asks for an immediate re-invoke (a
        decode step is pending) instead of parking for input.

        Correlation needs no input framing: the lane in-edge is SPSC and
        the driver assigns execution seqs in ring-write order under its
        submit lock, so the arrival counter here IS the driver seq."""
        from ray_tpu.experimental.channel import (
            STREAM_F_ERROR,
            STREAM_F_FINAL,
            STREAM_F_RAW,
            TAG_BYTES,
            TAG_ERROR,
            TAG_STOP,
            TAG_STREAM,
            TAG_TENSOR,
            ChannelClosed,
            pack_stream_frame,
        )

        def send(corr, flags, payload: bytes) -> None:
            frame = pack_stream_frame(corr, flags, payload)
            for out in outs:
                try:
                    out.write(frame, tag=TAG_STREAM)
                except Exception:
                    pass  # ring closed (teardown race)

        corr_counter = 0
        active = False
        while True:
            entries = []      # (corr, value) newly admitted this round
            stop = False
            while len(entries) < batch_max:
                if entries or active:
                    # a batch is running (or this round already admitted
                    # work): take only what is ALREADY queued — never
                    # stall a pending decode step waiting for arrivals
                    try:
                        if not ch.readable():
                            break
                    except Exception:
                        return  # channel closed (teardown race)
                try:
                    tag, payload = ch.read(timeout=None, to_device=device)
                except ChannelClosed:
                    stop = True
                    break
                except Exception:
                    return  # channel unlinked (teardown race)
                corr = corr_counter
                corr_counter += 1
                if tag == TAG_ERROR:
                    # upstream error passthrough: the request dies before
                    # admission, but its stream must still complete
                    send(corr, STREAM_F_FINAL | STREAM_F_ERROR, payload)
                elif tag == TAG_TENSOR or tag == TAG_BYTES:
                    entries.append((corr, payload))
                else:
                    entries.append((corr,
                                    serialization.deserialize(payload)))
            if stop and not active and not entries:
                propagate(TAG_STOP)
                return
            try:
                replies, active = invoke([entries])
            except Exception as e:  # noqa: BLE001 — ship to consumers
                # scheduler-step failure: fail the requests admitted THIS
                # round (the method owns bookkeeping for older ones, and
                # a dead process is handled by the driver's FSM probe)
                pl = error_payload(e)
                for corr, _ in entries:
                    send(corr, STREAM_F_FINAL | STREAM_F_ERROR, pl)
                replies, active = [], False
            for corr, kind, payload in replies:
                if kind == "error":
                    send(corr, STREAM_F_FINAL | STREAM_F_ERROR,
                         error_payload(payload))
                    continue
                flags = STREAM_F_FINAL if kind == "final" else 0
                if type(payload) is bytes:
                    flags |= STREAM_F_RAW
                else:
                    payload = serialization.serialize(payload).to_bytes()
                send(corr, flags, payload)
            if stop:
                propagate(TAG_STOP)
                return

    def _resolve_args(self, spec: TaskSpec):
        hints = spec.arg_hints or {}

        def resolve(v):
            kind, payload = v
            if kind == "ref":
                hint = hints.get(payload)
                if hint is not None and hint[0] == "inline":
                    # owner shipped the (small) arg bytes with the spec —
                    # no store round-trip at all
                    value = serialization.deserialize(hint[1])
                    if hint[2]:
                        raise value
                    return value
                node_hint = hint[1] if hint is not None else None
                return self._get_one(payload, None, node_hint)
            return serialization.deserialize(payload)

        args = [resolve(a) for a in spec.args]
        kwargs = {k: resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    def _execute(self, spec: TaskSpec, binding: Dict[str, List[int]]) -> None:
        restore_env = lambda: None  # noqa: E731
        span_cm = None
        try:
            if spec.task_id in self._cancelled:
                raise TaskCancelledError(f"task {spec.task_id.hex()} cancelled")
            # chaos point: "worker.exec[.<fn>]=crash@N" hard-kills this
            # worker before user code runs; raise/delay surface inline
            fault_injection.fire("worker.exec",
                                 spec.function_name.rsplit(".", 1)[-1])
            if spec.trace_ctx is not None:
                # child span joins the caller's trace (reference:
                # tracing_helper.py context propagation)
                from ray_tpu.util.tracing import task_span

                span_cm = task_span(spec)
                if span_cm is not None:
                    span_cm.__enter__()
            if binding:
                self._apply_accelerator_binding(binding)
            if spec.runtime_env:
                from .runtime_env import apply_runtime_env

                restore = apply_runtime_env(spec.runtime_env, self)
                # actor-creation envs persist for the actor's lifetime
                # (the worker is dedicated) — but only once the creation
                # SUCCEEDS; a failed creation returns this worker to the
                # shared pool, so its env must roll back. Plain-task envs
                # always restore.
                restore_env = restore
            args, kwargs = self._resolve_args(spec)
            self._current_task.task_id = spec.task_id
            self._current_task.actor_id = spec.actor_id
            self._current_task.name = spec.function_name
            if spec.is_actor_creation:
                cls = self.get_function(spec.function_id)
                instance = cls(*args, **kwargs)
                self._actors[spec.actor_id] = _ActorState(
                    instance, spec.actor_max_concurrency, spec.actor_is_async
                )
                restore_env = lambda: None  # noqa: E731 — creation OK:
                # the env persists for the actor's lifetime
                self._finish(spec, None)
            elif spec.actor_id is not None:
                st = self._actors[spec.actor_id]
                fn_name = spec.function_name.rsplit(".", 1)[-1]
                if fn_name == "__ray_terminate__":
                    self._finish(spec, None)
                    self.channel.send("exit")
                    time.sleep(0.2)
                    os._exit(0)
                if fn_name == "__compiled_exec__":
                    # install a resident compiled-graph executor thread
                    # (reference: compiled_dag_node.py do_exec_tasks :92)
                    self._start_compiled_exec(st, args[0])
                    self._finish(spec, None)
                    return
                if fn_name == "__compiled_setup__":
                    # Phase A of a cross-node compile: create this
                    # process's net-ring reader endpoints, return the
                    # ring-host dial-in for the producing processes
                    self._finish(spec, self._compiled_setup(args[0]))
                    return
                if fn_name == "__compiled_poison__":
                    # death-path broadcast: fail the local net readers
                    # under the DAG uid so loops parked on a dead peer's
                    # ring pop with ChannelClosed
                    from ray_tpu.core import net_ring

                    self._finish(spec, net_ring.poison_rings(args[0]))
                    return
                if fn_name == "__collective_init__":
                    # runtime-level hook so any actor can join a collective
                    # group without declaring a method (reference:
                    # create_collective_group's declarative setup)
                    from ray_tpu.collective import init_collective_group

                    init_collective_group(*args, **kwargs)
                    self._finish(spec, None)
                    return
                method = getattr(st.instance, fn_name)
                if st.exec_lock is not None:
                    # serialize with compiled-graph direct calls (the
                    # pool alone no longer owns all method executions)
                    with st.exec_lock:
                        result = method(*args, **kwargs)
                else:
                    result = method(*args, **kwargs)
                self._finish(spec, result)
            else:
                fn = self.get_function(spec.function_id)
                result = fn(*args, **kwargs)
                self._finish(spec, result)
        except Exception as e:  # noqa: BLE001
            self._send_error(spec, e)
        finally:
            if span_cm is not None:
                span_cm.__exit__(None, None, None)
            restore_env()
            self._current_task.task_id = None
            self._current_task.actor_id = None
            self._current_task.name = None

    def _apply_accelerator_binding(self, binding: Dict[str, List[int]]) -> None:
        """Set accelerator visibility env vars before user code imports jax.

        Reference: accelerators/tpu.py:155-195 sets TPU_VISIBLE_CHIPS etc;
        nvidia_gpu.py sets CUDA_VISIBLE_DEVICES.
        """
        self.accelerator_binding = binding
        if "TPU" in binding and "jax" not in sys.modules:
            chips = ",".join(str(i) for i in binding["TPU"])
            os.environ.setdefault("TPU_VISIBLE_CHIPS", chips)
        if "GPU" in binding:
            os.environ.setdefault(
                "CUDA_VISIBLE_DEVICES", ",".join(str(i) for i in binding["GPU"])
            )

    def _finish(self, spec: TaskSpec, result: Any) -> None:
        if spec.streaming:
            self._finish_streaming(spec, result)
            return
        rids = spec.return_ids()
        if spec.num_returns == 1:
            values = [result]
        elif spec.num_returns == 0:
            values = []
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                self._send_error(
                    spec,
                    ValueError(
                        f"task returned {len(values)} values, expected {spec.num_returns}"
                    ),
                )
                return
        results = []
        cfg = global_config()
        for oid, val in zip(rids, values):
            sobj = serialization.serialize(val)
            if sobj.total_bytes <= cfg.max_direct_call_object_size:
                results.append((oid, sobj.to_bytes(), False))
            else:
                offset = self.rpc.call("store", "create", oid, sobj.total_bytes)
                view = self.arena.view(offset, sobj.total_bytes)
                sobj.write_into_view(view)
                self.rpc.call("store", "seal", oid, False)
                results.append((oid, None, False))
        self.channel.send("done", spec.task_id, results, None)

    def _finish_streaming(self, spec: TaskSpec, result: Any) -> None:
        """Iterate a generator task: each yield becomes one "stream" item
        announcement to the node, which routes it to the OWNER over the
        direct reply chain (or to the head for head-path tasks). Small
        items ride inline in the announcement; large ones seal into the
        store first (the blocking seal rpc returning before the send keeps
        store-before-announce ordering). The primary return carries the
        final item count (reference: streaming generators,
        _raylet.pyx:1074-1317)."""
        from .ids import ObjectID as _OID

        cfg = global_config()
        count = 0
        try:
            if result is not None and hasattr(result, "__iter__"):
                for item in result:
                    sobj = serialization.serialize(item)
                    if sobj.total_bytes <= cfg.max_direct_call_object_size:
                        self.channel.send("stream", spec.task_id, count,
                                          sobj.to_bytes())
                    else:
                        oid = _OID.for_stream(spec.task_id, count)
                        self._store_object(oid, sobj, is_error=False)
                        self.channel.send("stream", spec.task_id, count,
                                          None)
                    count += 1
        except Exception as e:  # mid-stream user error
            self._send_error(spec, e)
            return
        spec.streaming = False  # primary return is a normal value now
        self._finish(spec, count)

    def stream_next(self, task_id, index: int, timeout=None, owner=None):
        # owner-side stream buffer first (direct-path streams this worker
        # owns); then the stream's owner route (subscribe straight to the
        # owning process over the node/peer reply channels); the head
        # only serves streams it actually records (head-path tasks)
        rep = self.direct.stream_next(task_id, index, timeout)
        if rep is not None:
            return rep
        if owner is not None:
            return self._stream_sub_rounds(owner, task_id, index, timeout)
        return self.rpc.call("rpc", "stream_next", task_id, index, timeout)

    def _stream_sub_rounds(self, owner, task_id, index: int,
                           timeout: Optional[float]):
        from .direct import bounded_sub_rounds

        return bounded_sub_rounds(
            lambda t: self.rpc.call("rpc", "stream_sub", owner, task_id,
                                    index, t, timeout=None), timeout)

    def stream_owner_route(self):
        """This process's stream-owner address, stamped into serialized
        generator handles so consumers subscribe here directly."""
        return ("w", self.node_hex, self.worker_id)

    def publish_stream(self, task_id) -> bool:
        # generator handle serialized out of this process (object_ref):
        # True = we own it and will serve subscribers
        return self.direct.publish_stream(task_id)

    def _serve_stream_sub(self, req_id: int, task_id, index: int,
                          timeout) -> None:
        """Owner side of one stream_sub round: read from this worker's
        own stream table and reply over the node channel ("srep")."""
        try:
            rep = self.direct.stream_next_remote(task_id, index, timeout)
        except Exception:
            rep = None
        if rep is None:
            rep = ("gone", "not the stream owner")
        try:
            self.channel.send("srep", req_id, rep)
        except (OSError, EOFError):
            pass  # node gone: the subscriber's round times out

    def _reply_stacks(self, req_id: int, duration_ms: int) -> None:
        """One bounded self-sample for the cluster stack dump, replied
        one-way over the node channel ("stack_rep")."""
        from ray_tpu.util import sampling_profiler

        try:
            text = sampling_profiler.collect_stacks(
                max(0.0, duration_ms / 1000.0))
        except Exception:
            text = ""  # sampler failure still replies (empty dump)
        try:
            self.channel.send("stack_rep", req_id, text)
        except (OSError, EOFError):
            pass  # node gone: the collector's deadline covers it

    def _send_error(self, spec: TaskSpec, exc: Exception) -> None:
        if isinstance(exc, TaskError):
            err = exc
        else:
            err = TaskError.from_exception(spec.function_name, exc)
        payload = serialization.serialize(err).to_bytes()
        results = [(oid, payload, True) for oid in spec.return_ids()]
        self.channel.send("done", spec.task_id, results,
                          type(exc).__name__)


def worker_main(argv=None) -> None:
    # SIGUSR1 -> all-thread dump to stderr (lands in the worker log file);
    # the debugging hook for wedged workers (reference: ray stack)
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True)
    parser.add_argument("--authkey", required=True)
    args = parser.parse_args(argv)
    # Transient refusals are normal when prestarted workers race the
    # node's accept handshake — retry with backoff before giving up. A
    # MISSING socket means the node is gone: exit quietly at once.
    channel = None
    deadline = time.monotonic() + 15.0
    delay = 0.05
    while True:
        try:
            channel = connect(args.address, bytes.fromhex(args.authkey))
            break
        except FileNotFoundError:
            sys.exit(0)  # node shut down before we started
        except (OSError, EOFError, Exception) as e:
            retriable = isinstance(e, (ConnectionError, EOFError, OSError))
            if retriable and time.monotonic() < deadline:
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
                continue
            if "Authentication" in type(e).__name__ or retriable:
                sys.exit(0)  # node gone / cluster key rotated
            raise
    channel.send("register", os.getpid())
    tag, payload = channel.recv()
    assert tag == "init", tag
    runtime = WorkerRuntime(channel, payload[0])
    object_ref_mod.set_runtime(runtime)
    from . import runtime as runtime_mod

    runtime_mod.set_current_runtime(runtime)
    from ray_tpu.util.metrics import start_report_thread

    start_report_thread(
        lambda snap: channel.send("metrics", snap),
        global_config().metrics_report_interval_ms / 1000.0)
    # flight-recorder spans ride the worker channel one-way ("spans");
    # the node stamps this worker's source id and forwards to the head
    if _fr.enabled():

        def _span_report_loop():
            period = max(
                0.25,
                global_config().flight_recorder_report_interval_ms / 1000.0)
            while True:
                time.sleep(period)
                try:
                    pl = _fr.drain()
                    if pl is not None:
                        channel.send("spans", pl)
                except Exception:
                    pass  # node gone: serve_forever exits us shortly

        threading.Thread(target=_span_report_loop, daemon=True,
                         name="flightrec-report").start()
    # ref-table reports ride the same worker channel one-way ("refs");
    # the node stamps this worker's source id and forwards to the head
    ref_tracker.start_report(
        lambda table: channel.send("refs", table),
        global_config().ref_report_interval_ms / 1000.0)
    # cluster events ride the worker channel one-way ("cevents"), same
    # shape as the metrics report; the node forwards them to the head
    from ray_tpu.util import events as events_mod

    events_mod.set_sink(
        lambda evs: channel.send("cevents", evs),
        global_config().cluster_event_flush_ms / 1000.0)
    if global_config().device_telemetry_enabled:
        from ray_tpu.util.device_telemetry import (observe_jax_import,
                                                    start_device_telemetry)

        observe_jax_import()  # compile events from process start, not tick 1
        start_device_telemetry(node_hex=runtime.node_hex)
    from ray_tpu.util.sampling_profiler import start_from_env

    _dump_profile = start_from_env()  # RAY_TPU_SAMPLER=<prefix> to enable
    if _dump_profile is not None:
        runtime._profile_dump = _dump_profile
    runtime.serve_forever()


if __name__ == "__main__":
    worker_main()
