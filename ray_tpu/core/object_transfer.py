"""Direct node-to-node chunked object transfer.

Analog of the reference's ObjectManager push/pull over gRPC
(src/ray/object_manager/object_manager.h:117, chunked per
object_manager_default_chunk_size ray_config_def.h:345): every node runs an
``ObjectServer``; a node needing an object asks the head only for *locations*
(addr + key), then pulls chunks straight from the source node's store into
its own arena — the driver's memory is never in the data path (the round-1
weakness: whole-object copies mediated by driver memory).

Wire protocol (multiprocessing.connection over TCP, HMAC-authenticated):
    puller -> ("pull", oid_binary)
    server -> ("meta", size, is_error) | ("missing",)
    server -> chunk bytes x ceil(size / chunk)      (send_bytes frames)
Connections are per-pull; the OS socket buffer provides backpressure.
"""

from __future__ import annotations

import contextlib
import threading
from multiprocessing import connection as mpc
from typing import Optional, Tuple

from .config import global_config
from .exceptions import ObjectLostError
from .ids import ObjectID
from .protocol import set_nodelay as _set_nodelay

# Serialize concurrent pulls of the same object into the same store: two
# racing create(oid) calls would free each other's in-flight arena offset
# (object_store.py create() reclaims a stale entry's extent). Entries are
# refcounted — a lock is only removed when no thread holds or awaits it.
_pull_locks: dict = {}
_pull_locks_guard = threading.Lock()


@contextlib.contextmanager
def _pull_guard(dest_store, oid: ObjectID):
    key = (id(dest_store), oid)
    with _pull_locks_guard:
        entry = _pull_locks.get(key)
        if entry is None:
            entry = _pull_locks[key] = [threading.Lock(), 0]
        entry[1] += 1
    try:
        with entry[0]:
            yield
    finally:
        with _pull_locks_guard:
            entry[1] -= 1
            if entry[1] <= 0:
                _pull_locks.pop(key, None)


class ObjectServer:
    """Per-node chunk server reading from the node's LocalObjectStore."""

    def __init__(self, store, authkey: bytes, host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None, node=None):
        self.store = store
        self.authkey = authkey
        self.node = node  # owning Node: enables the peer control session
        self._listener = mpc.Listener(address=(host, 0), family="AF_INET",
                                      authkey=authkey)
        bound_host, port = self._listener.address
        # a 0.0.0.0 bind is unroutable as an advertised address: publish
        # the node's real IP instead
        self.address: Tuple[str, int] = (
            (advertise_host, port)
            if advertise_host and bound_host in ("0.0.0.0", "::")
            else (bound_host, port))
        self._alive = True
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="object-server")
        self._thread.start()

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn = self._listener.accept()
            except Exception:
                if not self._alive:
                    return
                continue
            _set_nodelay(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn) -> None:
        chunk = global_config().object_transfer_chunk_size
        try:
            while True:
                msg = conn.recv()
                if msg[0] == "peer_hello" and self.node is not None:
                    # switch to the node-to-node control session (direct-
                    # task spillback; reference: NodeManagerService peer RPC)
                    self._serve_peer(conn)
                    return
                if msg[0] == "push":
                    self._serve_push(conn, msg)
                    continue
                if msg[0] != "pull":
                    break
                oid = ObjectID(msg[1])
                meta = self.store.read_meta(oid)
                if meta is None:
                    conn.send(("missing",))
                    continue
                size, is_err = meta
                conn.send(("meta", size, is_err))
                sent, aborted = 0, False
                while sent < size:
                    n = min(chunk, size - sent)
                    data = self.store.read_chunk(oid, sent, n)
                    if data is None or len(data) != n:
                        # deleted mid-stream: pad out the frame count so the
                        # puller's framing stays aligned, then it re-locates
                        conn.send_bytes(b"")
                        aborted = True
                        break
                    conn.send_bytes(data)
                    sent += n
                if aborted:
                    break
        except (EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_push(self, conn, msg) -> None:
        """Receive a pushed object (reference: push_manager.h:30 — the
        sender streams chunks without being asked) and continue the
        broadcast tree toward the delegated targets."""
        _, oid_b, size, is_err, targets = msg
        oid = ObjectID(oid_b)
        if self.store.contains(oid):
            # drain the frames to keep the stream aligned, then forward
            got = 0
            while got < size:
                got += len(conn.recv_bytes())
        else:
            with _pull_guard(self.store, oid):
                if self.store.contains(oid):
                    got = 0
                    while got < size:
                        got += len(conn.recv_bytes())
                else:
                    cfg = global_config()
                    if size <= cfg.max_direct_call_object_size:
                        buf = bytearray()
                        while len(buf) < size:
                            buf += conn.recv_bytes()
                        self.store.put_inline(oid, bytes(buf), is_err)
                    else:
                        offset, view = self.store.create(oid, size)
                        try:
                            got = 0
                            while got < size:
                                data = conn.recv_bytes()
                                view[got:got + len(data)] = data
                                got += len(data)
                        except Exception:
                            # pusher died mid-stream: drop the partial,
                            # unsealed entry so the arena space reclaims
                            # (mirrors _pull_one's failure cleanup)
                            try:
                                self.store.delete(oid)
                            except Exception:
                                pass
                            raise
                        self.store.seal(oid, is_err)
            if self.node is not None:
                try:
                    self.node.head.on_object_sealed(oid, self.node.hex)
                except Exception:
                    pass
        conn.send(("ok",))
        if targets and self.node is not None:
            threading.Thread(
                target=self.node.push_object_to, args=(oid, list(targets)),
                daemon=True, name=f"bcast-{oid.hex()[:6]}").start()

    def _serve_peer(self, conn) -> None:
        """Session with a peer node: accept forwarded direct tasks; the
        executing node replies over this same channel ("pdone")."""
        import pickle

        from .protocol import Channel

        ch = Channel(conn)
        try:
            while self._alive:
                try:
                    tag, payload = ch.recv()
                except (EOFError, OSError, TypeError):
                    return  # origin gone; stolen tasks fail in finally
                if tag == "psubmit":
                    try:
                        spec = pickle.loads(payload[0])
                    except Exception:
                        continue
                    self.node.submit_direct(spec, ("peer", ch))
                elif tag == "pcancel":
                    self.node.cancel_direct(payload[0], payload[1])
                elif tag == "pload":
                    self.node.on_peer_load(*payload)
                elif tag == "psteal":
                    # idle peer pulls queued work (work stealing)
                    self.node._serve_steal(ch, payload[0])
                elif tag == "pdone":
                    # completion of a task this node handed to the peer
                    self.node.on_peer_done(*payload)
                elif tag == "pstream":
                    # stream item of a task this node handed to the peer
                    self.node.on_peer_stream_item(*payload)
        finally:
            self.node.on_peer_session_closed(ch)

    def close(self) -> None:
        self._alive = False
        try:
            self._listener.close()
        except OSError:
            pass


def pull_object(address, authkey: bytes, oid: ObjectID,
                dest_store=None) -> Optional[Tuple[object, bool]]:
    """Pull one object from a remote ObjectServer.

    Small objects return (bytes, is_error). Large ones stream chunk-by-chunk
    into ``dest_store``'s arena (never materializing the whole payload in
    this process beyond one chunk) and return (("arena", offset, size),
    is_error); with no dest_store large pulls assemble bytes. Returns None
    if the remote no longer has the object (caller re-locates).
    """
    cfg = global_config()
    if dest_store is None:
        return _pull_one(address, authkey, oid, None, cfg)
    with _pull_guard(dest_store, oid):
        # double-check: a racing pull may have landed it already
        if dest_store.contains(oid):
            info = dest_store.entry_info(oid)
            if info is not None:
                off, size, is_err = info
                return ("arena", off, size), is_err
            payload, is_err = dest_store.get_payload(oid)
            return bytes(payload), is_err
        return _pull_one(address, authkey, oid, dest_store, cfg)


def _pull_one(address, authkey: bytes, oid: ObjectID, dest_store, cfg):
    conn = None
    created = False
    try:
        conn = mpc.Client(address=tuple(address), family="AF_INET",
                          authkey=authkey)
        _set_nodelay(conn)
        conn.send(("pull", oid.binary()))
        msg = conn.recv()
        if msg[0] != "meta":
            return None
        size, is_err = msg[1], msg[2]
        inline = size <= cfg.max_direct_call_object_size or dest_store is None
        if inline:
            buf = bytearray()
            while len(buf) < size:
                data = conn.recv_bytes()
                if not data:
                    return None
                buf += data
            return bytes(buf), is_err
        offset, view = dest_store.create(oid, size)
        created = True
        got = 0
        while got < size:
            data = conn.recv_bytes()
            if not data:
                dest_store.delete(oid)
                return None
            view[got:got + len(data)] = data
            got += len(data)
        dest_store.seal(oid, is_err)
        return ("arena", offset, size), is_err
    except (EOFError, OSError, ValueError):
        # connect refused / source died mid-stream: drop any partial,
        # unsealed arena entry so the space is reclaimable, and report
        # "unavailable" so the caller re-locates
        if created:
            try:
                dest_store.delete(oid)
            except Exception:
                pass
        return None
    finally:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass


def push_object(address, authkey: bytes, oid: ObjectID, src_store,
                targets=()) -> bool:
    """Stream one object to a peer's object server, delegating onward
    delivery of ``targets`` (the binary-broadcast-tree edge; reference:
    push_manager.h chunked push). Returns False if the source no longer
    has the object or the target is unreachable."""
    cfg = global_config()
    meta = src_store.read_meta(oid)
    if meta is None:
        return False
    size, is_err = meta
    conn = None
    try:
        conn = mpc.Client(address=tuple(address), family="AF_INET",
                          authkey=authkey)
        _set_nodelay(conn)
        conn.send(("push", oid.binary(), size, is_err, list(targets)))
        chunk = cfg.object_transfer_chunk_size
        sent = 0
        while sent < size:
            n = min(chunk, size - sent)
            data = src_store.read_chunk(oid, sent, n)
            if data is None or len(data) != n:
                return False  # evicted mid-push; receiver re-locates
            conn.send_bytes(data)
            sent += n
        ack = conn.recv()
        return ack and ack[0] == "ok"
    except (EOFError, OSError, ValueError):
        return False
    finally:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass


def fan_out_push(src_store, authkey: bytes, oid: ObjectID,
                 targets) -> int:
    """Binomial broadcast: deliver ``oid`` to every (hex, addr) target,
    delegating half of the remainder to each pushed peer so total depth
    is O(log N) (reference: the broadcast shape of push_manager +
    ray's object-broadcast envelope '1 GiB to 50+ nodes')."""
    targets = list(targets)
    pushed = 0
    while targets:
        (t_hex, t_addr), rest = targets[0], targets[1:]
        half = (len(rest) + 1) // 2
        delegate, targets = rest[:half], rest[half:]
        if push_object(t_addr, authkey, oid, src_store, targets=delegate):
            pushed += 1 + len(delegate)
        else:
            # unreachable peer: reclaim its delegation for ourselves
            targets = delegate + targets
    return pushed


def pull_payload(address, authkey: bytes, oid: ObjectID):
    """Pull as bytes regardless of size (driver-side get)."""
    res = pull_object(address, authkey, oid, dest_store=None)
    if res is None:
        raise ObjectLostError(oid, "remote node no longer has the object")
    return res
