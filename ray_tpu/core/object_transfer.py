"""Direct node-to-node chunked object transfer (zero-copy data plane).

Analog of the reference's ObjectManager push/pull over gRPC
(src/ray/object_manager/object_manager.h:117, chunked per
object_manager_default_chunk_size ray_config_def.h:345): every node runs an
``ObjectServer``; a node needing an object asks the head only for *locations*
(addr + key), then pulls chunks straight from the source node's store into
its own arena — the driver's memory is never in the data path.

Data-plane design (this module's three throughput pillars):

1. **Pooled connections** — a per-peer pool of authenticated, reusable
   ``multiprocessing.connection`` TCP clients (bounded size, idle timeout,
   health check on checkout) shared by ``pull_object`` / ``push_object`` /
   ``fan_out_push``. The reference keeps persistent gRPC channels per
   remote node; a fresh TCP+HMAC handshake per object was this layer's
   round-5 hot-path tax.
2. **Arena-direct chunked transfers** — the puller allocates the
   destination extent first (size from the transfer header) and receives
   each chunk straight into ``memoryview`` slices of the shm mmap via
   ``recv_bytes_into`` (zero intermediate copies, constant memory); the
   sender streams ``send_bytes(view, offset, n)`` over the sealed extent
   pinned by ``LocalObjectStore.open_read`` — no ``bytes`` payload is ever
   materialized on either side.
3. **Striped multi-peer pulls** — objects >= ``object_stripe_threshold``
   with >=2 holders (GCS location table) are split into contiguous
   stripes pulled in parallel from different holders into disjoint arena
   slices, with per-stripe failover to the remaining holders when a peer
   dies mid-transfer (reference: pull_manager.h parallel chunked pulls).

Wire protocol (multiprocessing.connection over TCP, HMAC-authenticated;
one server-side thread per connection, many requests per connection):
    puller -> ("pull", oid_binary)                  whole object
    puller -> ("pullr", oid_binary, start, length)  byte range (stripes)
    puller -> ("stat", oid_binary)                  metadata only
    server -> ("meta", size, is_error) | ("missing",)
    server -> RAW byte stream of exactly the requested range
Control messages use the connection's pickle framing; the payload body is
a raw unframed stream (``os.sendfile`` from the tmpfs arena fd on the
sender, ``os.readv`` straight into the destination mmap on the receiver —
CPython's ``recv_bytes_into`` copies through an internal BytesIO, so the
framed API cannot be zero-copy). A sender that loses the object
mid-stream closes the connection; the receiver treats the short read as
"unavailable" and re-locates. Aborted/err'd connections are discarded
from the pool, clean exchanges are pooled for reuse.
"""

from __future__ import annotations

import contextlib
import os
import socket as _socket
import threading
import time
from multiprocessing import connection as mpc
from typing import Dict, List, Optional, Sequence, Tuple

from .config import global_config
from .exceptions import ObjectLostError
from .ids import ObjectID
from .protocol import set_nodelay as _set_nodelay

from ray_tpu.util.metrics import Counter

# transfer metrics: merged into the head registry by the existing metrics
# report threads, so they show up in /metrics and /api/metrics/history
_m_pool_hits = Counter("object_transfer_pool_hits_total",
                       "pooled connection checkouts that reused a socket")
_m_pool_misses = Counter("object_transfer_pool_misses_total",
                         "pooled connection checkouts that dialed fresh")
_m_pool_evicted = Counter("object_transfer_pool_evicted_total",
                          "pooled connections dropped (idle/unhealthy)")
_m_bytes_pulled = Counter("object_transfer_bytes_pulled_total",
                          "object payload bytes pulled from peers")
_m_bytes_pushed = Counter("object_transfer_bytes_pushed_total",
                          "object payload bytes pushed to peers")
_m_stripe_pulls = Counter("object_transfer_stripe_pulls_total",
                          "large pulls striped across multiple holders")
_m_stripe_retries = Counter("object_transfer_stripe_retries_total",
                            "stripe failovers to a surviving holder")

_CONN_ERRS = (EOFError, OSError, ValueError, BufferError)

# transfer sockets move multi-MB bodies: widen the kernel buffers (the
# ~200KB defaults throttle loopback/LAN streaming) — best effort
_SOCK_BUF = 4 << 20


def _tune_conn(conn) -> None:
    _set_nodelay(conn)
    try:
        s = _socket.socket(fileno=os.dup(conn.fileno()))
        try:
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, _SOCK_BUF)
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, _SOCK_BUF)
        finally:
            s.close()
    except OSError:
        pass


# ---- raw body streaming (no per-chunk framing) ---------------------------- #


def _read_exact_into(fd: int, view: memoryview) -> None:
    """Fill ``view`` from the socket fd — reads land directly in the
    destination buffer (an arena mmap slice), zero intermediate copies.
    MSG_WAITALL lets the kernel loop until the buffer fills (one syscall
    for multi-MB bodies instead of one per socket-buffer drain)."""
    total = view.nbytes
    if total == 0:
        return
    try:
        s = _socket.socket(fileno=os.dup(fd))
    except OSError:
        s = None
    got = 0
    try:
        while got < total:
            if s is not None:
                n = s.recv_into(view[got:], 0, _socket.MSG_WAITALL)
            else:
                n = os.readv(fd, [view[got:]])
            if n == 0:
                raise EOFError("transfer stream truncated")
            got += n
    finally:
        if s is not None:
            s.close()


def _drain_exact(fd: int, count: int) -> None:
    """Consume exactly ``count`` raw body bytes (duplicate push)."""
    if count <= 0:
        return
    buf = memoryview(bytearray(min(count, 1 << 20)))
    left = count
    while left > 0:
        n = os.readv(fd, [buf[:min(left, buf.nbytes)]])
        if n == 0:
            raise EOFError("transfer stream truncated")
        left -= n


def _write_all(fd: int, view) -> None:
    view = memoryview(view)
    off = 0
    total = view.nbytes
    while off < total:
        off += os.write(fd, view[off:])


_sendfile_broken = False


def _send_body(sock_fd: int, handle, start: int, length: int) -> None:
    """Stream ``length`` bytes of a pinned arena extent: os.sendfile from
    the tmpfs backing fd (payload never enters user space), falling back
    to plain writes from the mmap view."""
    global _sendfile_broken
    if not _sendfile_broken:
        try:
            sent = 0
            base = handle.offset + start
            while sent < length:
                n = os.sendfile(sock_fd, handle.fd, base + sent,
                                length - sent)
                if n == 0:
                    raise EOFError("peer closed mid-send")
                sent += n
            return
        except OSError as e:
            import errno

            if sent == 0 and e.errno in (errno.EINVAL, errno.ENOSYS,
                                         errno.ENOTSOCK):
                _sendfile_broken = True  # fall through to mmap writes
            else:
                raise
    _write_all(sock_fd, handle.view[start:start + length])


# --------------------------------------------------------------------------- #
# Connection pool
# --------------------------------------------------------------------------- #


class ConnectionPool:
    """Per-peer pool of authenticated, reusable transfer connections.

    Checkout is exclusive (a connection is never shared between threads);
    release returns it for reuse unless the protocol exchange ended off a
    message boundary (``discard``). Health check on checkout: a healthy
    idle transfer connection has no readable data — ``poll(0)`` returning
    True means server EOF or stray bytes, either way unusable.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._idle: Dict[tuple, List[Tuple[object, float]]] = {}
        self.hits = 0
        self.misses = 0
        self.evicted = 0

    def acquire(self, address, authkey: bytes):
        addr = tuple(address)
        cfg = global_config()
        if cfg.object_pool_enabled:
            now = time.monotonic()
            while True:
                with self._lock:
                    entries = self._idle.get(addr)
                    entry = entries.pop() if entries else None
                if entry is None:
                    break
                conn, ts = entry
                if now - ts > cfg.object_pool_idle_timeout_s:
                    self._drop(conn)
                    continue
                try:
                    if conn.closed or conn.poll(0):
                        self._drop(conn)
                        continue
                except OSError:
                    self._drop(conn)
                    continue
                with self._lock:
                    self.hits += 1
                _m_pool_hits.inc()
                return conn
        conn = mpc.Client(address=addr, family="AF_INET", authkey=authkey)
        _tune_conn(conn)
        with self._lock:
            self.misses += 1
        _m_pool_misses.inc()
        return conn

    def release(self, address, conn, discard: bool = False) -> None:
        cfg = global_config()
        if discard or not cfg.object_pool_enabled:
            self._drop(conn, count=discard)
            return
        addr = tuple(address)
        now = time.monotonic()
        expired: List[object] = []
        with self._lock:
            # global idle sweep: addresses never acquired again (removed
            # peers) would otherwise keep their sockets forever — the
            # lazy per-address timeout in acquire() can't reach them
            for a in list(self._idle):
                entries = self._idle[a]
                keep = [(c, ts) for c, ts in entries
                        if now - ts <= cfg.object_pool_idle_timeout_s]
                expired.extend(c for c, ts in entries
                               if now - ts > cfg.object_pool_idle_timeout_s)
                if keep:
                    self._idle[a] = keep
                else:
                    del self._idle[a]
            entries = self._idle.setdefault(addr, [])
            if len(entries) >= cfg.object_pool_connections_per_peer:
                stale = entries.pop(0)[0]  # bound: recycle the oldest slot
                entries.append((conn, now))
                conn = stale
            else:
                entries.append((conn, now))
                conn = None
        for c in expired:
            self._drop(c)
        if conn is not None:
            self._drop(conn)

    def _drop(self, conn, count: bool = True) -> None:
        if count:
            with self._lock:
                self.evicted += 1
            _m_pool_evicted.inc()
        try:
            conn.close()
        except OSError:
            pass

    def close_all(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, {}
        for entries in idle.values():
            for conn, _ts in entries:
                try:
                    conn.close()
                except OSError:
                    pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evicted": self.evicted,
                "idle": sum(len(v) for v in self._idle.values()),
            }


_pool = ConnectionPool()


def pool_stats() -> Dict[str, int]:
    """Process-wide transfer connection-pool counters (bench/tests)."""
    return _pool.stats()


def close_pool() -> None:
    """Close idle pooled connections (node/daemon shutdown)."""
    _pool.close_all()


# Serialize concurrent pulls of the same object into the same store: two
# racing create(oid) calls would free each other's in-flight arena offset
# (object_store.py create() reclaims a stale entry's extent). Entries are
# refcounted — a lock is only removed when no thread holds or awaits it.
_pull_locks: dict = {}
_pull_locks_guard = threading.Lock()


@contextlib.contextmanager
def _pull_guard(dest_store, oid: ObjectID):
    key = (id(dest_store), oid)
    with _pull_locks_guard:
        entry = _pull_locks.get(key)
        if entry is None:
            entry = _pull_locks[key] = [threading.Lock(), 0]
        entry[1] += 1
    try:
        with entry[0]:
            yield
    finally:
        with _pull_locks_guard:
            entry[1] -= 1
            if entry[1] <= 0:
                _pull_locks.pop(key, None)


# --------------------------------------------------------------------------- #
# Server
# --------------------------------------------------------------------------- #


class ObjectServer:
    """Per-node chunk server reading from the node's LocalObjectStore."""

    def __init__(self, store, authkey: bytes, host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None, node=None):
        self.store = store
        self.authkey = authkey
        self.node = node  # owning Node: enables the peer control session
        self._listener = mpc.Listener(address=(host, 0), family="AF_INET",
                                      authkey=authkey)
        bound_host, port = self._listener.address
        # a 0.0.0.0 bind is unroutable as an advertised address: publish
        # the node's real IP instead
        self.address: Tuple[str, int] = (
            (advertise_host, port)
            if advertise_host and bound_host in ("0.0.0.0", "::")
            else (bound_host, port))
        self._alive = True
        # live accepted connections: close() severs them so a "dead" node
        # really aborts its in-flight transfers (striped-pull failover)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="object-server")
        self._thread.start()

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn = self._listener.accept()
            except Exception:
                if not self._alive:
                    return
                continue
            if not self._alive:
                # a blocked accept() can hand out one last connection
                # after close(); a closed server must serve nothing
                try:
                    conn.close()
                except OSError:
                    pass
                return
            _tune_conn(conn)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn) -> None:
        chunk = global_config().object_transfer_chunk_size
        try:
            while True:
                msg = conn.recv()
                tag = msg[0]
                if tag == "peer_hello" and self.node is not None:
                    # switch to the node-to-node control session (direct-
                    # task spillback; reference: NodeManagerService peer RPC)
                    self._serve_peer(conn)
                    return
                if tag == "push":
                    self._serve_push(conn, msg)
                    continue
                if tag == "stat":
                    meta = self.store.read_meta(ObjectID(msg[1]))
                    conn.send(("missing",) if meta is None
                              else ("meta", meta[0], meta[1]))
                    continue
                if tag not in ("pull", "pullr"):
                    break
                oid = ObjectID(msg[1])
                meta = self.store.read_meta(oid)
                if meta is None:
                    conn.send(("missing",))
                    continue
                size, is_err = meta
                conn.send(("meta", size, is_err))
                if tag == "pull":
                    start, length = 0, size
                else:
                    start = max(0, int(msg[2]))
                    length = int(msg[3])
                    if length < 0:
                        length = size - start
                    length = max(0, min(length, size - start))
                if not self._send_range(conn, oid, start, length, chunk):
                    break  # aborted mid-stream: close, framing must not skew
        except (EOFError, OSError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _send_range(self, conn, oid: ObjectID, start: int, length: int,
                    chunk: int) -> bool:
        """Stream payload[start:start+length] as a raw byte body. Arena-
        resident objects go zero-copy (sendfile from the tmpfs fd / writes
        from the pinned mmap); inline/spilled entries fall back to copying
        chunk reads. Returns False when the entry vanished mid-stream —
        the caller closes the connection, which the puller reads as a
        truncated body and re-locates."""
        fd = conn.fileno()
        with self.store.open_read(oid) as handle:
            if handle is not None:
                if start + length > handle.view.nbytes:
                    # entry was deleted + re-put at a different size between
                    # the meta reply and this pin: streaming would send the
                    # wrong byte count (or bytes past the extent) — abort
                    return False
                _send_body(fd, handle, start, length)
                return True
        sent = 0
        while sent < length:
            n = min(chunk, length - sent)
            data = self.store.read_chunk(oid, start + sent, n)
            if data is None or len(data) != n:
                return False
            _write_all(fd, data)
            sent += n
        return True

    def _serve_push(self, conn, msg) -> None:
        """Receive a pushed object (reference: push_manager.h:30 — the
        sender streams chunks without being asked) straight into a
        pre-allocated arena extent, and continue the broadcast tree toward
        the delegated targets."""
        _, oid_b, size, is_err, targets = msg
        oid = ObjectID(oid_b)
        fd = conn.fileno()
        if self.store.contains(oid):
            # drain the raw body to keep the stream aligned, then forward
            _drain_exact(fd, size)
        else:
            with _pull_guard(self.store, oid):
                if self.store.contains(oid):
                    _drain_exact(fd, size)
                else:
                    cfg = global_config()
                    if size <= cfg.max_direct_call_object_size:
                        buf = bytearray(size)
                        _read_exact_into(fd, memoryview(buf))
                        self.store.put_inline(oid, bytes(buf), is_err,
                                              transfer=True)
                    else:
                        offset, view = self.store.create(oid, size,
                                                         transfer=True)
                        try:
                            _read_exact_into(fd, view)
                        except Exception:
                            # pusher died mid-stream: drop the partial,
                            # unsealed entry so the arena space reclaims
                            # (mirrors _pull_one's failure cleanup)
                            try:
                                self.store.delete(oid)
                            except Exception:
                                pass
                            raise
                        self.store.seal(oid, is_err)
            if self.node is not None:
                try:
                    self.node.head.on_object_sealed(oid, self.node.hex)
                except Exception:
                    pass
        conn.send(("ok",))
        if targets and self.node is not None:
            threading.Thread(
                target=self.node.push_object_to, args=(oid, list(targets)),
                daemon=True, name=f"bcast-{oid.hex()[:6]}").start()

    def _serve_peer(self, conn) -> None:
        """Session with a peer node: accept forwarded direct tasks; the
        executing node replies over this same channel ("pdone")."""
        import pickle

        from .protocol import Channel

        ch = Channel(conn)
        try:
            while self._alive:
                try:
                    tag, payload = ch.recv()
                except (EOFError, OSError, TypeError):
                    return  # origin gone; stolen tasks fail in finally
                if tag == "psubmit":
                    try:
                        spec = pickle.loads(payload[0])
                    except Exception:
                        continue
                    self.node.submit_direct(spec, ("peer", ch))
                elif tag == "pcancel":
                    self.node.cancel_direct(payload[0], payload[1])
                elif tag == "pload":
                    self.node.on_peer_load(*payload)
                elif tag == "psteal":
                    # idle peer pulls queued work (work stealing)
                    self.node._serve_steal(ch, payload[0])
                elif tag == "pdone":
                    # completion of a task this node handed to the peer
                    self.node.on_peer_done(*payload)
                elif tag == "pstream":
                    # stream item of a task this node handed to the peer
                    self.node.on_peer_stream_item(*payload)
                elif tag == "psub":
                    # stream subscription for an owner in this process
                    self.node._serve_peer_stream_sub(ch, *payload)
                elif tag == "psubrep":
                    # reply to a subscription this node forwarded out
                    self.node._ssub_reply(*payload)
        finally:
            self.node.on_peer_session_closed(ch)

    def close(self) -> None:
        self._alive = False
        from .protocol import close_listener

        close_listener(self._listener)  # wakes the parked accept()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)  # accept() raises once closed


# --------------------------------------------------------------------------- #
# Pull
# --------------------------------------------------------------------------- #


def pull_object(address, authkey: bytes, oid: ObjectID,
                dest_store=None) -> Optional[Tuple[object, bool]]:
    """Pull one object from a remote ObjectServer over a pooled connection.

    Small objects return (bytes, is_error). Large ones stream chunk-by-
    chunk straight into ``dest_store``'s arena extent (zero intermediate
    copies) and return (("arena", offset, size), is_error); with no
    dest_store large pulls assemble bytes. Returns None if the remote no
    longer has the object (caller re-locates).
    """
    cfg = global_config()
    if dest_store is None:
        return _pull_one(address, authkey, oid, None, cfg)
    with _pull_guard(dest_store, oid):
        # double-check: a racing pull may have landed it already
        local = _local_result(dest_store, oid)
        if local is not None:
            return local
        return _pull_one(address, authkey, oid, dest_store, cfg)


def pull_object_striped(addresses: Sequence, authkey: bytes, oid: ObjectID,
                        dest_store,
                        on_peer_failed=None) -> Optional[Tuple[object, bool]]:
    """Pull one large object striped across multiple holders.

    ``addresses`` lists the object servers of every known holder. Objects
    below ``object_stripe_threshold`` (or with a single reachable holder)
    fall back to a plain pooled pull. Each stripe lands in a disjoint
    slice of one pre-allocated arena extent; a stripe whose peer dies
    mid-transfer retries against the remaining holders (failover emits a
    cluster event so operators see the degraded peer). Returns None only
    when no holder could serve the object.

    ``on_peer_failed(addr)`` (optional) is invoked for every holder that
    could not serve the object (unreachable, missing, died mid-stream) —
    even when the pull ultimately succeeds via failover — so callers can
    invalidate stale locations in the directory.
    """
    addresses = [tuple(a) for a in addresses]
    if not addresses:
        return None
    failed: set = set()

    def note_failed(addr) -> None:
        failed.add(tuple(addr))

    cfg = global_config()
    try:
        if dest_store is None or len(addresses) < 2:
            res = pull_object(addresses[0], authkey, oid, dest_store)
            if res is None:
                note_failed(addresses[0])
            return res
        with _pull_guard(dest_store, oid):
            local = _local_result(dest_store, oid)
            if local is not None:
                return local
            meta = None
            for a in addresses:
                meta = _stat_one(a, authkey, oid)
                if meta is not None:
                    break
                note_failed(a)
            if meta is not None and meta[0] >= cfg.object_stripe_threshold:
                res = _pull_striped(addresses, authkey, oid, meta[0],
                                    meta[1], dest_store, cfg, note_failed)
                if res is not None:
                    return res
            for a in addresses:
                res = _pull_one(a, authkey, oid, dest_store, cfg)
                if res is not None:
                    return res
                note_failed(a)
            return None
    finally:
        if on_peer_failed is not None:
            for a in failed:
                try:
                    on_peer_failed(a)
                except Exception:
                    pass


def _local_result(dest_store, oid: ObjectID):
    if not dest_store.contains(oid):
        return None
    info = dest_store.entry_info(oid)
    if info is not None:
        off, size, is_err = info
        return ("arena", off, size), is_err
    payload, is_err = dest_store.get_payload(oid)
    return bytes(payload), is_err


def _stat_one(address, authkey: bytes,
              oid: ObjectID) -> Optional[Tuple[int, bool]]:
    """(size, is_error) from one holder, or None if unreachable/missing."""
    addr = tuple(address)
    try:
        conn = _pool.acquire(addr, authkey)
    except Exception:
        return None
    reuse = False
    try:
        conn.send(("stat", oid.binary()))
        msg = conn.recv()
        reuse = msg[0] in ("meta", "missing")
        return (msg[1], msg[2]) if msg[0] == "meta" else None
    except _CONN_ERRS:
        return None
    finally:
        _pool.release(addr, conn, discard=not reuse)


def _pull_one(address, authkey: bytes, oid: ObjectID, dest_store, cfg):
    addr = tuple(address)
    try:
        conn = _pool.acquire(addr, authkey)
    except Exception:
        return None  # connect refused/auth failure: caller re-locates
    reuse = False
    created = False
    try:
        conn.send(("pull", oid.binary()))
        msg = conn.recv()
        if msg[0] != "meta":
            reuse = msg[0] == "missing"  # clean miss: conn still aligned
            return None
        size, is_err = msg[1], msg[2]
        fd = conn.fileno()
        if size <= cfg.max_direct_call_object_size or dest_store is None:
            buf = bytearray(size)
            _read_exact_into(fd, memoryview(buf))
            reuse = True
            _m_bytes_pulled.inc(size)
            return bytes(buf), is_err
        offset, view = dest_store.create(oid, size, transfer=True)
        created = True
        _read_exact_into(fd, view)
        dest_store.seal(oid, is_err)
        created = False
        reuse = True
        _m_bytes_pulled.inc(size)
        return ("arena", offset, size), is_err
    except _CONN_ERRS:
        # connect refused / source died mid-stream: drop any partial,
        # unsealed arena entry so the space is reclaimable, and report
        # "unavailable" so the caller re-locates
        if created:
            try:
                dest_store.delete(oid)
            except Exception:
                pass
        return None
    finally:
        _pool.release(addr, conn, discard=not reuse)


def _pull_striped(addresses, authkey: bytes, oid: ObjectID, size: int,
                  is_err: bool, dest_store, cfg, note_failed=None):
    peers = addresses[:max(2, cfg.object_stripe_max_peers)]
    stripe = (size + len(peers) - 1) // len(peers)
    ranges = [(i * stripe, min(stripe, size - i * stripe))
              for i in range(len(peers)) if i * stripe < size]
    offset, view = dest_store.create(oid, size, transfer=True)
    ok = [False] * len(ranges)

    def pull_stripe(idx: int) -> None:
        start, length = ranges[idx]
        # holder preference rotates so stripes spread across peers;
        # failover walks the remaining holders
        order = peers[idx % len(peers):] + peers[:idx % len(peers)]
        for attempt, a in enumerate(order):
            if attempt > 0:
                _m_stripe_retries.inc()
                _emit_stripe_failover(oid, order[attempt - 1], a, idx)
            if _pull_range(a, authkey, oid, start, length, view, size):
                ok[idx] = True
                return
            if note_failed is not None:
                note_failed(a)

    threads = [threading.Thread(target=pull_stripe, args=(i,), daemon=True,
                                name=f"stripe-{oid.hex()[:6]}-{i}")
               for i in range(len(ranges))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if all(ok):
        dest_store.seal(oid, is_err)
        _m_bytes_pulled.inc(size)
        _m_stripe_pulls.inc()
        return ("arena", offset, size), is_err
    try:
        dest_store.delete(oid)
    except Exception:
        pass
    return None


def _pull_range(address, authkey: bytes, oid: ObjectID, start: int,
                length: int, view, expect_size: int) -> bool:
    """Receive payload[start:start+length] into the matching arena slice."""
    addr = tuple(address)
    try:
        conn = _pool.acquire(addr, authkey)
    except Exception:
        return False
    reuse = False
    try:
        conn.send(("pullr", oid.binary(), start, length))
        msg = conn.recv()
        if msg[0] != "meta":
            reuse = msg[0] == "missing"
            return False
        if msg[1] != expect_size:
            # this holder's copy disagrees with the size the stripes were
            # cut from (re-put under the same oid): the server clamps the
            # range to ITS size, so MSG_WAITALL would block forever on
            # the missing tail — fail the stripe (and discard the conn,
            # whose stream now carries the clamped body) instead
            return False
        _read_exact_into(conn.fileno(), view[start:start + length])
        reuse = True
        return True
    except _CONN_ERRS:
        return False
    finally:
        _pool.release(addr, conn, discard=not reuse)


def _emit_stripe_failover(oid: ObjectID, failed_addr, next_addr,
                          stripe_idx: int) -> None:
    try:
        from ray_tpu.util import events as events_mod

        events_mod.emit(
            "WARNING", events_mod.SOURCE_OBJECT_STORE,
            f"stripe {stripe_idx} failover for object {oid.hex()[:8]}: "
            f"peer {failed_addr[0]}:{failed_addr[1]} failed mid-transfer, "
            f"retrying on {next_addr[0]}:{next_addr[1]}",
            entity_id=oid.hex(), stripe=stripe_idx,
            failed_peer=f"{failed_addr[0]}:{failed_addr[1]}")
    except Exception:
        pass


# --------------------------------------------------------------------------- #
# Push
# --------------------------------------------------------------------------- #


def push_object(address, authkey: bytes, oid: ObjectID, src_store,
                targets=()) -> bool:
    """Stream one object to a peer's object server, delegating onward
    delivery of ``targets`` (the binary-broadcast-tree edge; reference:
    push_manager.h chunked push). Sends straight from the pinned arena
    extent when resident. Returns False if the source no longer has the
    object or the target is unreachable."""
    cfg = global_config()
    meta = src_store.read_meta(oid)
    if meta is None:
        return False
    size, is_err = meta
    addr = tuple(address)
    try:
        conn = _pool.acquire(addr, authkey)
    except Exception:
        return False
    reuse = False
    try:
        conn.send(("push", oid.binary(), size, is_err, list(targets)))
        chunk = cfg.object_transfer_chunk_size
        fd = conn.fileno()
        sent = 0
        with src_store.open_read(oid) as handle:
            # nbytes check: the entry may have been deleted + re-put at a
            # different size since read_meta above — the announced size is
            # the contract, so a mismatched extent must not stream
            if handle is not None and handle.view.nbytes == size:
                _send_body(fd, handle, 0, size)
                sent = size
        while sent < size:
            n = min(chunk, size - sent)
            data = src_store.read_chunk(oid, sent, n)
            if data is None or len(data) != n:
                return False  # evicted mid-push; receiver re-locates
            _write_all(fd, data)
            sent += n
        ack = conn.recv()
        reuse = bool(ack) and ack[0] == "ok"
        if reuse:
            _m_bytes_pushed.inc(size)
        return reuse
    except _CONN_ERRS:
        return False
    finally:
        _pool.release(addr, conn, discard=not reuse)


def fan_out_push(src_store, authkey: bytes, oid: ObjectID,
                 targets) -> int:
    """Binomial broadcast: deliver ``oid`` to every (hex, addr) target,
    delegating half of the remainder to each pushed peer so total depth
    is O(log N) (reference: the broadcast shape of push_manager +
    ray's object-broadcast envelope '1 GiB to 50+ nodes')."""
    targets = list(targets)
    pushed = 0
    while targets:
        (t_hex, t_addr), rest = targets[0], targets[1:]
        half = (len(rest) + 1) // 2
        delegate, targets = rest[:half], rest[half:]
        if push_object(t_addr, authkey, oid, src_store, targets=delegate):
            pushed += 1 + len(delegate)
        else:
            # unreachable peer: reclaim its delegation for ourselves
            targets = delegate + targets
    return pushed


def pull_payload(address, authkey: bytes, oid: ObjectID):
    """Pull as bytes regardless of size (driver-side get)."""
    res = pull_object(address, authkey, oid, dest_store=None)
    if res is None:
        raise ObjectLostError(oid, "remote node no longer has the object")
    return res
