"""Accelerator detection & visibility management — TPU-first.

Analog of ``python/ray/_private/accelerators/`` in the reference, with the
TPU manager (``tpu.py:71 TPUAcceleratorManager``) as the primary citizen:
chip counts are detected from GKE/GCE-style env vars without importing jax
(importing jax would claim the chip in the driver; workers must own devices).
Visibility is applied per-worker via TPU_VISIBLE_CHIPS (reference:
tpu.py:155-195) by worker_runtime._apply_accelerator_binding.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

VALID_TPU_CHIP_COUNTS = (1, 2, 4, 8)  # reference: tpu.py:141


def detect_num_tpu_chips() -> int:
    """Detect TPU chips on this host without initializing jax.

    Order (reference: tpu.py:48 GKE env vars then GCE metadata; metadata
    server is unreachable here so env-only, plus the axon tunnel exposes one
    chip when TPU_SKIP_MDS_QUERY-style markers are present):
    """
    v = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if v:
        try:
            dims = [int(x) for x in v.split(",")]
            n = 1
            for d in dims:
                n *= d
            return n
        except ValueError:
            pass
    v = os.environ.get("TPU_VISIBLE_CHIPS") or os.environ.get("TPU_CHIPS")
    if v:
        return len([c for c in v.split(",") if c != ""])
    for marker in ("TPU_NAME", "TPU_WORKER_ID", "AXON_TPU", "JAX_PLATFORMS"):
        val = os.environ.get(marker, "")
        if marker == "JAX_PLATFORMS" and "tpu" not in val and "axon" not in val:
            continue
        if val:
            return 1
    # /dev/accel* device files are the local giveaway on TPU VMs
    try:
        accels = [f for f in os.listdir("/dev") if f.startswith("accel")]
        if accels:
            return len(accels)
    except OSError:
        pass
    return 0


def tpu_pod_resources() -> Dict[str, float]:
    """Slice-head resources (e.g. TPU-v5e-8-head) for gang scheduling
    (reference: tpu.py advertises TPU-{type}-head on worker 0)."""
    out: Dict[str, float] = {}
    acc_type = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. v5litepod-8
    worker_id = os.environ.get("TPU_WORKER_ID", "0")
    if acc_type and worker_id == "0":
        out[f"TPU-{acc_type}-head"] = 1.0
    return out


def detect_resources(
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    num_gpus: Optional[int] = None,
    extra: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    total: Dict[str, float] = {}
    total["CPU"] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    n_tpu = num_tpus if num_tpus is not None else detect_num_tpu_chips()
    if n_tpu:
        total["TPU"] = float(n_tpu)
        total.update(tpu_pod_resources())
    if num_gpus:
        total["GPU"] = float(num_gpus)
    total["memory"] = float(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))
    total["object_store_memory"] = 0.0
    if extra:
        total.update({k: float(v) for k, v in extra.items()})
    total = {k: v for k, v in total.items() if v}
    return total
