"""Config/flag system.

Analog of the reference's ``src/ray/common/ray_config_def.h`` (216 RAY_CONFIG
entries overridable by ``RAY_<name>`` env vars) — a single typed registry of
every runtime tunable, overridable with ``RAY_TPU_<NAME>`` environment
variables, snapshotted at cluster start and shipped to workers.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields, asdict
from typing import Any, Dict


def _env(name: str, default, typ):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return typ(raw)


# Bootstrap-time environment variables read OUTSIDE the Config snapshot.
# These are consulted before a cluster (and therefore a Config) exists —
# connect addresses, credentials, per-process identity — so they cannot be
# Config fields: a daemon adopts the head's Config at registration, which
# would clobber per-node values like the advertised IP.  graftlint's
# config-hygiene check requires every direct RAY_TPU_* read in the tree to
# appear here (and in docs/configuration.md); everything else must go
# through a Config field + global_config().
BOOTSTRAP_ENV_VARS = {
    "RAY_TPU_ADDRESS": "head address ray_tpu.init() connects to",
    "RAY_TPU_CLUSTER_KEY": "cluster auth key (hex) for client connects",
    "RAY_TPU_NODE_IP": "routable IP this node advertises to peers",
    "RAY_TPU_JOB_TOKEN": "dashboard job-submission auth token",
    "RAY_TPU_USAGE_STATS_ENABLED": "opt-in usage-stats reporting",
    "RAY_TPU_WORKFLOW_STORAGE": "workflow checkpoint storage URI",
    "RAY_TPU_RUNTIME_ENV_PLUGINS": "entry points for runtime_env plugins",
}


@dataclass
class Config:
    # ---- object store / plasma (reference: ray_config_def.h:199,345,398,614) ----
    max_direct_call_object_size: int = 100 * 1024  # inline vs shared-mem threshold
    object_store_memory: int = 512 * 1024 * 1024  # default shm arena bytes
    object_store_full_delay_ms: int = 10
    object_transfer_chunk_size: int = 5 * 1024 * 1024
    object_spilling_enabled: bool = True
    object_spilling_dir: str = ""  # defaults to session dir /spill
    min_spilling_size: int = 1 * 1024 * 1024
    max_io_workers: int = 4
    # arena usage fraction past which the store emits a WARNING cluster
    # event naming the top consumers by creation callsite (<= 0 disables)
    object_store_high_watermark: float = 0.8

    # ---- object data plane (node-to-node transfer; object_transfer.py) ----
    # pooled, reusable authenticated connections per peer object server
    # (reference: ObjectManager keeps persistent gRPC channels per remote;
    # a fresh TCP+HMAC handshake per pull was the round-5 hot-path tax)
    object_pool_enabled: bool = True
    object_pool_connections_per_peer: int = 4
    object_pool_idle_timeout_s: float = 60.0
    # striped multi-peer pulls: objects >= threshold with >=2 holders are
    # split into per-holder stripes pulled in parallel into disjoint arena
    # slices (reference: chunked parallel pulls, pull_manager.h)
    object_stripe_threshold: int = 8 * 1024 * 1024
    object_stripe_max_peers: int = 4
    # cross-host compiled-graph rings (core/net_ring.py): Go-Back-N
    # retransmission cadence — a message whose ack made no progress for
    # this long is re-sent (the recovery path after a dropped data/ack
    # message or a reconnected session; the model-checked re-ack rule
    # makes every retransmission idempotent)
    net_ring_retransmit_ms: int = 50

    # ---- scheduler (reference: ray_config_def.h:179,185,190) ----
    scheduler_spread_threshold: float = 0.5
    scheduler_top_k_fraction: float = 0.2
    scheduler_top_k_absolute: int = 1
    raylet_report_resources_period_ms: int = 100

    # ---- workers ----
    num_workers_soft_limit: int = -1  # -1 => num_cpus
    worker_maximum_startup_concurrency: int = 0  # 0 => num_cpus
    worker_prestart_count: int = 2  # eagerly forked at node start (reference:
    # worker_pool.h:163 num_prestarted_python_workers)
    worker_register_timeout_s: float = 60.0
    worker_lease_idle_timeout_s: float = 5.0
    # plain CPU tasks staged per worker beyond the running one (lease
    # pipelining, reference: normal_task_submitter.h worker_to_lease_entry_
    # + max_tasks_in_flight; hides the done->dispatch round trip)
    worker_pipeline_depth: int = 2

    # ---- direct (head-bypass) task path ----
    # Eligible plain CPU tasks execute via the submitter's node + one-hop
    # peer spillback, with batched event reports to the head (reference:
    # normal_task_submitter.cc — the GCS is out of the normal-task path)
    direct_task_enabled: bool = True
    # actor method calls go caller->actor-node directly (head keeps the
    # lifecycle FSM only); off = every a.m.remote() routes via the head
    direct_actor_enabled: bool = True
    # spill to a peer when the local queue exceeds factor * max_workers
    direct_spill_queue_factor: float = 4.0
    # executor nodes batch (object-location + observability) events to the
    # head: flush at this many events or this age, whichever first
    direct_event_batch_size: int = 200
    direct_event_flush_ms: int = 20
    # direct tasks may hold at most this fraction of a node's worker slots
    # while head-dispatched (resource-bound) work is waiting — prevents a
    # direct-task flood from starving scheduler-placed tasks
    direct_slot_fraction: float = 0.85
    # idle nodes pull queued direct tasks from the deepest-queued peer
    # (work stealing — spillback is otherwise submit-time-only); 0 = off
    direct_steal_enabled: bool = True
    direct_steal_min_queue: int = 2  # only steal from peers at least this deep
    direct_steal_interval_ms: int = 50
    # published (cross-process) streams that reached EOF with the local
    # handle dropped are retained for remote subscribers — bounded FIFO:
    # past this many, the oldest purge and stragglers see owner-gone
    # (the owner-side analog of the old head stream-record TTL)
    published_stream_retain_max: int = 256

    # ---- tasks / fault tolerance (reference: ray_config_def.h:138,414,835) ----
    task_retry_delay_ms: int = 0
    lineage_pinning_enabled: bool = True
    # owner-side lineage for direct-path store-resident results: specs
    # retained for reconstruction after the sealing node dies (reference:
    # object_recovery_manager.h + max_lineage_bytes-style cap); 0 = off
    direct_lineage_max: int = 4096
    # actor re-creation backoff: the first restart waits delay_ms, each
    # further restart doubles it up to max_delay_ms (reference:
    # gcs_actor_manager restart backoff); delay_ms=0 restarts immediately
    actor_restart_delay_ms: int = 0
    actor_restart_max_delay_ms: int = 10_000
    # head restart: how long a daemon keeps re-dialing a bounced head
    # before giving up and shutting down, and how long the restarted head
    # waits for known daemons to re-register before declaring them dead
    # (their actors then fail over per max_restarts)
    head_rejoin_timeout_s: float = 30.0
    daemon_rejoin_grace_s: float = 10.0
    # node prober: period * threshold = grace before a silent daemon is
    # declared dead (generous default — pongs share the daemon's handler
    # pool, so a saturated 1-core host must not look dead)
    health_check_period_ms: int = 2000
    health_check_failure_threshold: int = 10

    # ---- head record GC (reference: task-event cap semantics,
    # ray_config_def.h task_events_max_num_task_in_gcs area) ----
    # settled head task records fold into the capped event ring after this
    # TTL (kept while their results are referenced — lineage — or while
    # the actor they created is alive); 0 disables the sweeper
    task_record_ttl_s: float = 120.0
    task_record_gc_period_s: float = 15.0

    # ---- observability ----
    log_to_driver: bool = True  # tail worker stdout/stderr to the driver
    task_events_enabled: bool = True
    task_events_max_buffered: int = 100_000
    metrics_report_interval_ms: int = 10_000
    event_log_enabled: bool = True
    # structured cluster event log (util/events.py -> GCS ring + JSONL).
    # emit() delivers inline; the flush cadence only governs re-delivery
    # after a failed send, so it stays low-frequency (per-worker wakeups
    # add jitter to latency-sensitive loops)
    cluster_events_max_buffered: int = 10_000
    cluster_event_flush_ms: int = 1000
    cluster_events_log_max_bytes: int = 64 * 1024 * 1024
    # head-side metrics time-series rings (/api/metrics/history)
    metrics_history_enabled: bool = True
    metrics_history_interval_ms: int = 5_000
    metrics_history_max_samples: int = 360
    # per-process JAX/TPU device telemetry (HBM gauges + jax.monitoring)
    device_telemetry_enabled: bool = True
    device_telemetry_interval_ms: int = 10_000
    # XLA compile observatory (util/xla_observatory.py): per-process
    # registry of observed jitted executables (compile wall time,
    # cost/memory analyses, aval fingerprints) feeding the standard
    # metrics/span channels. The kill switch exists so bench.py
    # --xla-bench can measure the observation cost (BENCH_XLA.json,
    # <=1% of the spmd step)
    xla_observatory_enabled: bool = True
    # recompile-storm detector (train/health.py): >= trigger NEW-aval
    # recompiles of one program within a monitor tick raises one
    # WARNING naming the program and the shape churn; it clears after
    # clear_ticks consecutive quiet ticks (hysteresis — no flapping)
    xla_storm_trigger_recompiles: int = 3
    xla_storm_clear_ticks: int = 2
    # roofline ceiling overrides for the xla report, in FLOP/s and
    # bytes/s per chip; 0 = auto-detect from the device kind (TPU
    # table) or fall back to nominal trend-only CPU values
    xla_peak_flops: float = 0.0
    xla_peak_hbm_bytes: float = 0.0
    # object/memory observability (core/ref_tracker.py): per-process
    # ObjectRef accounting joined head-side into the `ray memory` analog
    # (util/state.memory_summary, /api/memory). The kill switch exists so
    # bench_objects.py --check can measure the accounting's own cost.
    ref_accounting_enabled: bool = True
    # capture creator callsites (file:line:function) at ref creation —
    # a sys._getframe walk per put/submit, so opt-in (the `ray memory`
    # RAY_record_ref_creation_sites analog)
    record_ref_creation_sites: bool = False
    # worker -> head ref-table report cadence (rides the worker channel
    # one-way, same shape as the metrics report)
    ref_report_interval_ms: int = 1000
    # serve request-path observability: request ids + per-stage latency
    # histograms + JSONL access logs + slow-request events (serve/
    # observability.py). One switch for the whole layer so the bench can
    # measure its overhead; the access log has its own gate
    serve_observability_enabled: bool = True
    serve_access_log_enabled: bool = True
    serve_access_log_max_bytes: int = 64 * 1024 * 1024
    # requests slower end-to-end than this emit a WARNING cluster event
    # with the stage breakdown; per-deployment override via
    # @serve.deployment(slow_request_threshold_s=...); <= 0 disables
    serve_slow_request_threshold_s: float = 1.0
    # flight recorder (util/flight_recorder.py): always-on per-process
    # span rings behind `python -m ray_tpu timeline`. The hot path is a
    # flag test when off and ~two clock reads + a tuple store when on
    # (overhead bench-gated in BENCH_TRACE.json)
    flight_recorder: bool = True
    # ring capacity in span records per process (rounded up to a power
    # of two; one record is one fixed-size tuple slot)
    flight_recorder_events: int = 65536
    # seconds of trailing spans a crash dump keeps (fault-injection
    # crashes and attributed-death paths write
    # session_dir/logs/flightrec/<proc>-<pid>-<ts>.json)
    flight_recorder_dump_window_s: float = 10.0
    # worker/daemon -> head span-drain cadence (rides the worker channel
    # one-way like the metrics report; drops are harmless — the next
    # drain re-ships nothing, spans are consumed on drain)
    flight_recorder_report_interval_ms: int = 2000
    # goodput observatory (util/goodput.py + train/health.py): a head
    # service folds the span/metrics planes into a badput ledger and
    # runs the straggler/regression/TTRT detectors on this cadence
    health_monitor_enabled: bool = True
    health_monitor_interval_ms: int = 5_000
    # straggler detector: a host (or MPMD stage) whose mean step-span
    # duration exceeds the cluster median by trigger_x raises an
    # edge-triggered WARNING; it clears below clear_x. The gap between
    # the two is the hysteresis band — a host oscillating across one
    # threshold cannot flap events. min_spans is the evidence floor.
    straggler_trigger_x: float = 1.5
    straggler_clear_x: float = 1.2
    straggler_min_spans: int = 4
    # regression detector: recent-window mean vs rolling baseline on
    # the head's metrics-history rings (train step time, tokens/s,
    # serve dispatch latency). trigger/clear are degradation factors
    # with the same hysteresis contract as the straggler knobs;
    # min_samples points must exist before a series is judged and the
    # last `window` of them form the recent mean.
    regression_trigger_x: float = 1.3
    regression_clear_x: float = 1.1
    regression_min_samples: int = 8
    regression_window: int = 3
    # time-to-recovered-throughput: after a death event, throughput is
    # "recovered" once back within this fraction of the pre-fault
    # rolling baseline (0.2 = within 20%)
    ttrt_recovery_fraction: float = 0.2
    # cluster stack dump (`python -m ray_tpu stack`): how long each
    # process samples its threads for the one-shot collapsed dump
    stack_dump_duration_ms: int = 200
    # duration floor: spans shorter than this skip the ring, leaving
    # only the clock reads on the hot path — what keeps the recorder
    # inside the <=3% dag-bench overhead gate at microsecond dispatch
    # rates. The default sits above the ring-wait jitter of an
    # oversubscribed host (waits stretch into the hundreds of us there,
    # and recording every one re-inflates the hot path exactly when the
    # box is slowest); step-scale spans (pipeline fwd/bwd, SPMD phases,
    # bubbles, batch drains) sit at ms scale, far above it, and the
    # ring STALL COUNTERS still aggregate every wait regardless.
    # Lower it (or set 0: record everything) to trace micro behavior.
    flight_recorder_min_span_us: float = 500.0

    # ---- serve compiled dispatch plane (serve/compiled_dispatch.py) ----
    # route unary requests over long-lived compiled graphs (one ring-pair
    # lane per replica, microsecond dispatch) instead of eager remote();
    # the eager handle path stays as automatic fallback (streaming,
    # worker/client-side handles, oversized payloads, lane build failure)
    serve_compiled_dispatch: bool = True
    # per-replica admission window: ring slots per lane = bounded
    # in-flight per replica = continuous-batch ceiling. Structural
    # backpressure: a full window overflows to the eager path (within
    # the budget) instead of queueing. Per-deployment override via
    # @serve.deployment(max_inflight=...)
    serve_max_inflight: int = 8
    # per-deployment concurrency budget at the dispatching process:
    # once this many requests are in flight AND every replica window is
    # full, new requests shed with serve.BackPressureError instead of
    # queueing without bound. 0 = unlimited (never shed). Override via
    # @serve.deployment(concurrency_budget=...)
    serve_concurrency_budget: int = 0
    # ring slot size per lane message; requests/replies larger than this
    # fall back to the eager path for that call
    serve_channel_slot_bytes: int = 1 * 1024 * 1024
    # prewarmed worker pool per node: keep this many IDLE pre-forked
    # workers on standby so a serve scale-out consumes a warm process
    # instead of paying the fork+import cold start on the ramp step
    # (kills the scale-out p99 tail). 0 = off.
    serve_prewarm_pool_size: int = 0

    # ---- fault injection (reference: testing_asio_delay_us :824) ----
    testing_delay_ms: str = ""  # "handler1=ms,handler2=ms" injected latency
    # artificially slow EVERY control RPC the head serves (ms/op). The
    # head-freeness proof: with this at >=50, direct actor-call p50 and
    # cross-process stream items/s must not move (bench_core --actor-bench)
    test_head_delay_ms: int = 0
    # deterministic chaos harness (core/fault_injection.py): named failure
    # points armed with crash/raise/drop/fail/delay actions at exact hit
    # counts, e.g. "worker.exec.boom=crash@2;wire.send.sync=drop@1+".
    # Ships with the Config snapshot, so one env var arms every process.
    test_fault_spec: str = ""

    # ---- debug assertions ----
    # dynamic lock-order checking (core/lock_debug.py): runtime locks
    # created through lock_debug.tracked_* keep a thread-local acquisition
    # stack and a global order graph, raising LockOrderViolation the
    # moment two locks are ever taken in both orders — the runtime
    # counterpart of graftlint's static lock-order check. Test-only: adds
    # a graph probe per acquire, so off by default.
    debug_lock_order: bool = False

    # ---- TPU (reference: custom_unit_instance_resources :735) ----
    # Resources tracked per unit instance (index-assignable like CUDA devices).
    unit_instance_resources: str = "TPU,GPU,neuron_cores,NPU,HPU"

    # ---- collective ----
    collective_timeout_s: float = 300.0

    # ---- sharded training (train/spmd.py) ----
    # mesh axis spec for the SPMD train loop, e.g. "data=4,fsdp=2";
    # empty = pure data-parallel over all local devices. The same
    # config runs devices=1 and devices=N — with one device every
    # collective folds to the identity.
    train_mesh: str = ""
    # donate the carried train state on the jit step (params/optimizer
    # buffers alias their outputs — in-place update instead of a full
    # state copy per step). Toggle exists so benches can price it.
    train_donate: bool = True
    # batches kept in flight by the sharded to_jax ingest path
    # (per-shard device_put double-buffering: host→device transfer of
    # batch N+1 overlaps compute on batch N)
    train_ingest_prefetch: int = 2
    # fsdp param gather schedule for the shard_map step: "streamed"
    # gathers each scanned layer inside the scan, prefetching layer i+1
    # while layer i computes (ZeRO-3 prefetch; O(tree/L) peak param
    # residency); "upfront" bulk-gathers the whole tree first. Folds to
    # upfront on meshes without an fsdp axis.
    train_gather: str = "streamed"

    def __post_init__(self):
        for f in fields(self):
            cur = getattr(self, f.name)
            setattr(self, f.name, _env(f.name, cur, type(cur)))

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "Config":
        cfg = cls()
        for k, v in json.loads(s).items():
            setattr(cfg, k, v)
        return cfg

    def delay_for(self, handler: str) -> float:
        """Fault-injection latency (seconds) for a named handler, 0 if none."""
        if not self.testing_delay_ms:
            return 0.0
        for part in self.testing_delay_ms.split(","):
            if "=" in part:
                name, ms = part.split("=", 1)
                if name == handler:
                    return float(ms) / 1000.0
        return 0.0


_global_config: Config | None = None


def global_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def set_global_config(cfg: Config):
    global _global_config
    _global_config = cfg
