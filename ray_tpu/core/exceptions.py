"""Error hierarchy, analog of ``python/ray/exceptions.py`` in the reference."""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get`` on the caller side.

    Mirrors the reference's RayTaskError (python/ray/exceptions.py): carries the
    remote traceback text and the original exception (when picklable).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.traceback_str, self.cause))

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        try:
            import pickle

            pickle.dumps(exc)
            cause = exc
        except Exception:
            cause = None
        return cls(function_name, tb, cause)


class ActorError(TaskError):
    """An actor method raised."""


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed, killed, or exceeded max_restarts)."""

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(reason)


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ObjectLostError(RayTpuError):
    """Object cannot be found/reconstructed anywhere in the cluster."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        super().__init__(reason)


class ObjectStoreFullError(RayTpuError):
    """Shared-memory store is out of memory even after eviction/spilling."""


class TaskCancelledError(RayTpuError):
    """Task was cancelled via ``cancel()``."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(timeout=...)`` expired."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment failed to materialize."""


class NodeDiedError(RayTpuError):
    """The node hosting the computation died."""


class PlacementGroupError(RayTpuError):
    """Placement group creation/scheduling failure."""
