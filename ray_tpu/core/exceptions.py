"""Error hierarchy, analog of ``python/ray/exceptions.py`` in the reference."""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get`` on the caller side.

    Mirrors the reference's RayTaskError (python/ray/exceptions.py): carries the
    remote traceback text and the original exception (when picklable).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.traceback_str, self.cause))

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        try:
            import pickle

            pickle.dumps(exc)
            cause = exc
        except Exception:
            cause = None
        return cls(function_name, tb, cause)


class ActorError(TaskError):
    """An actor method raised."""


def format_death_cause(cause: str, node_hex: str | None = None,
                       pid: int | None = None,
                       worker_hex: str | None = None) -> str:
    """The one formatter every death cause goes through: attribute WHERE
    the death happened (node hex, worker pid/hex) alongside WHY, so no
    surface — eager call, stream subscriber, compiled-DAG ref — ever
    reports a bare timeout or an unattributed "actor died". Cause
    strings travel the wire as text (actor FSM ``death_cause``), so the
    attribution is baked into the string once, at the process that
    observed the death."""
    where = []
    if node_hex:
        where.append(f"node {node_hex[:8]}")
    if pid:
        where.append(f"worker pid {pid}")
    if worker_hex:
        where.append(f"worker {worker_hex[:8]}")
    return f"{cause} ({', '.join(where)})" if where else cause


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed, killed, or exceeded max_restarts) —
    or, with ``restarting=True``, this CALL died with an incarnation
    that the runtime is restarting (the call's retry budget was
    exhausted even though the actor itself will come back)."""

    def __init__(self, actor_id=None, reason: str = "actor died",
                 restarting: bool = False):
        self.actor_id = actor_id
        self.reason = reason
        self.restarting = restarting
        msg = reason
        if actor_id is not None:
            try:
                msg = f"actor {actor_id.hex()[:8]}: {reason}"
            except AttributeError:
                pass
        if restarting:
            msg += " [actor is restarting: new calls will reach the " \
                   "next incarnation]"
        super().__init__(msg)

    def __reduce__(self):
        # default Exception pickling would re-call __init__ with the
        # formatted message as actor_id — carry the real fields instead
        return (type(self), (self.actor_id, self.reason, self.restarting))


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ObjectLostError(RayTpuError):
    """Object cannot be found/reconstructed anywhere in the cluster."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        super().__init__(reason)


class ObjectStoreFullError(RayTpuError):
    """Shared-memory store is out of memory even after eviction/spilling."""


class TaskCancelledError(RayTpuError):
    """Task was cancelled via ``cancel()``."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(timeout=...)`` expired."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment failed to materialize."""


class NodeDiedError(RayTpuError):
    """The node hosting the computation died."""


class PlacementGroupError(RayTpuError):
    """Placement group creation/scheduling failure."""
