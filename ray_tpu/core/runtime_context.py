"""Runtime context (reference: ``python/ray/runtime_context.py``)."""

from __future__ import annotations

from typing import Dict, List, Optional


class RuntimeContext:
    def __init__(self, info: dict):
        self._info = info

    def get_job_id(self) -> str:
        return self._info["job_id"].hex()

    def get_node_id(self) -> str:
        return self._info["node_id"]

    def get_node_ip(self) -> str:
        """Routable IP of this process's node (reference:
        ``ray.util.get_node_ip_address``); loopback for in-process nodes."""
        return self._info.get("node_ip", "127.0.0.1")

    def get_worker_id(self) -> str:
        wid = self._info["worker_id"]
        return wid.hex() if isinstance(wid, bytes) else str(wid)

    def get_task_id(self) -> Optional[str]:
        tid = self._info.get("task_id")
        return tid.hex() if tid is not None else None

    def get_actor_id(self) -> Optional[str]:
        aid = self._info.get("actor_id")
        return aid.hex() if aid is not None else None

    def get_accelerator_ids(self) -> Dict[str, List[str]]:
        return {k: [str(i) for i in v]
                for k, v in self._info.get("accelerator_ids", {}).items()}

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_assigned_resources(self) -> Dict[str, float]:
        return self._info.get("assigned_resources", {})


def get_runtime_context() -> RuntimeContext:
    from .runtime import get_current_runtime

    rt = get_current_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return RuntimeContext(rt.runtime_context())
