"""Standalone node daemon: joins a head over TCP from another OS process/host.

The multi-host split the reference gets from separate raylet processes
(src/ray/raylet/main.cc:123): ``python -m ray_tpu.core.node_daemon
--address <head_host:port> --key <hex>`` runs a full Node (worker pool +
shm arena + object server) in its own process. The Node's upcalls into the
"head" go through ``RemoteHead``, which forwards them over the registration
channel; object payloads never traverse it — they move via direct chunked
node-to-node pulls (object_transfer.py).

Registration handshake (head side: runtime.py Head._register_daemon):
    daemon -> ("hello", {})
    head   -> ("welcome", {node_hex, job_id, config})   # head config adopted
    daemon -> ("node_ready", {resources, labels, object_addr, pid})
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import tempfile
import threading
import time
from typing import Optional

from .config import Config, global_config, set_global_config
from .exceptions import ObjectLostError
from .ids import JobID, NodeID, ObjectID
from .protocol import Channel, RpcClient, connect, parse_address


# daemon->head messages that MUST NOT be lost across a head bounce: they
# carry state the head can't re-derive (results, seals, death reports,
# batched direct events). Buffered while the link is down and replayed in
# order after re-registration. Telemetry (sync/metrics/logs/pongs) and
# refs reports re-arrive on their own cadence and are droppable.
_RELIABLE_TAGS = frozenset({
    "task_finished", "sealed", "sealed_payload", "stream_item",
    "worker_exit", "worker_crashed", "dispatch_worker_failed",
    "devents", "cevents", "pub1",
})
_OUTBOX_MAX = 10_000


class RemoteHead:
    """Daemon-side proxy implementing the Head interface a Node calls.

    Survives a head bounce: on link EOF (or an explicit ``reregister``
    from a restarted head that spotted our stale epoch) the reader
    re-dials the head address, re-registers under the SAME node hex with
    a replay snapshot (store manifest + holder leases + hosted actors —
    Node.replay_snapshot), and flushes the reliable-message outbox, so
    the restarted head converges to the pre-crash view without any
    daemon-resident state having moved."""

    def __init__(self, channel: Channel, welcome: dict, cluster_key: bytes,
                 address=None):
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        self.channel = channel
        self.rpc = RpcClient(channel)
        self.job_id = JobID(welcome["job_id"])
        self.node_hex: str = welcome["node_hex"]
        self.cluster_key = cluster_key
        self.address = address  # head endpoint, re-dialed after a bounce
        self.epoch = welcome.get("epoch", 1)  # head incarnation
        # the node_ready payload, retained so re-registration can resend
        # it (main() fills it in before the first send)
        self.ready_payload: dict = {}
        self._outbox: "deque" = deque(maxlen=_OUTBOX_MAX)
        self._outbox_lock = threading.Lock()
        self._closing = False
        # no head-backed pin view: store eviction/delete protection on a
        # daemon is the node-local holder lease (Node._arg_leases) — the
        # old per-object is_pinned head RPC is gone from the wire
        self.ref_counts = None
        self.node = None  # set after Node construction
        self.stopped = threading.Event()
        # fetch_local prefetch kicks (timeout=0 waits): one in-flight
        # background pull per object across concurrent waits
        self._prefetching: set = set()
        self._prefetch_lock = threading.Lock()
        self.cluster_view: list = []          # syncer-broadcast membership
        self.cluster_view_version: int = 0
        # handlers can block on node/store locks (e.g. store_delete vs a
        # reclaim holding the store lock mid pin-check RPC): run them off
        # the read loop so "rep" delivery is never queued behind them.
        # dispatch-family messages keep a dedicated single thread so actor
        # task ordering (send order to the worker channel) is preserved.
        self._ordered_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="head-dispatch")
        self._handler_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="head-msg")
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="head-link")
        self._reader.start()

    def close(self) -> None:
        """Daemon teardown: drop the head link and reap the handler
        machinery (reader exits on channel EOF / the shutdown tag)."""
        self._closing = True
        try:
            self.channel.close()
        except Exception:
            pass
        self._ordered_pool.shutdown(wait=False)
        self._handler_pool.shutdown(wait=False)
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2.0)

    # ------------------------------------------------------------ channel

    def _send(self, tag: str, *payload) -> None:
        try:
            if self._outbox:
                # opportunistic drain BEFORE this message (keeps order):
                # covers stragglers that failed into the outbox after
                # _reconnect's bounded flush — without this, a seal
                # parked in that window would wait for the NEXT
                # disconnect to ever be delivered
                with self._outbox_lock:
                    while self._outbox:
                        t, p = self._outbox[0]
                        # deliberate: the lock exists precisely to
                        # serialize outbox drains (peek-send-pop must
                        # not interleave across threads or messages
                        # deliver twice); nothing else is taken under it
                        # graftlint: ignore[blocking-under-lock]
                        self.channel.send(t, *p)
                        self._outbox.popleft()
            self.channel.send(tag, *payload)
        except (OSError, EOFError, ValueError):
            # link down (head bouncing, or gone for good): reliable
            # messages park in the outbox and replay after rejoin; the
            # reader thread owns reconnection and final-death decisions
            if tag in _RELIABLE_TAGS and not self._closing:
                self._outbox.append((tag, payload))

    def _read_loop(self) -> None:
        while True:
            try:
                tag, payload = self.channel.recv()
            except (EOFError, OSError):
                self.rpc.fail_all(ConnectionError("head link lost"))
                if self._closing or self.stopped.is_set():
                    self.stopped.set()
                    return
                # head bounce? re-dial and re-register under the same
                # node hex; only a timed-out reconnect kills the daemon
                if self.address is None or not self._reconnect():
                    self.stopped.set()
                    return
                continue
            if tag == "rep":
                self.rpc.handle_reply(*payload)
            elif tag == "shutdown":
                self._closing = True
                self.stopped.set()
                return
            elif tag == "reregister":
                # the head restarted and spotted our stale epoch on the
                # syncer: drop this link; the EOF path re-registers
                self.rpc.fail_all(ConnectionError("head restarted"))
                try:
                    self.channel.close()
                except Exception:
                    pass
                if self.address is None or not self._reconnect():
                    self.stopped.set()
                    return
            elif tag in ("dispatch", "dispatch_worker", "cancel",
                         "kill_worker"):
                self._ordered_pool.submit(self._handle, tag, payload)
            else:
                self._handler_pool.submit(self._handle, tag, payload)

    def _reconnect(self) -> bool:
        """Re-dial the bounced head and re-register (same node hex, full
        replay snapshot), then flush the reliable outbox. Runs on the
        reader thread; other threads' sends keep failing into the outbox
        until the swapped-in channel is live."""
        from .config import global_config
        from .protocol import check_protocol, connect

        deadline = time.monotonic() + global_config().head_rejoin_timeout_s
        while time.monotonic() < deadline and not self._closing:
            try:
                ch = connect(self.address, self.cluster_key)
            except Exception:
                time.sleep(0.3)
                continue
            try:
                ch.send("hello", {"rejoin": self.node_hex})
                tag, (welcome,) = ch.recv()
                assert tag == "welcome", tag
                check_protocol(welcome)
                if welcome["node_hex"] != self.node_hex:
                    raise ConnectionError("head did not honor rejoin hex")
                ready = dict(self.ready_payload)
                ready["replay"] = (self.node.replay_snapshot()
                                   if self.node is not None else {})
                ch.send("node_ready", ready)
                # replay reliable messages IN ORDER before the swap so
                # buffered results precede anything sent afterwards.
                # Peek-send-pop: a send failure mid-flush leaves the
                # message AT THE FRONT for the next reconnect attempt
                # (pop-first would silently drop it — the exact lost-seal
                # bug the outbox exists to prevent)
                with self._outbox_lock:
                    while self._outbox:
                        t, p = self._outbox[0]
                        ch.send(t, *p)
                        self._outbox.popleft()
                self.epoch = welcome.get("epoch", self.epoch + 1)
                self.channel = ch
                self.rpc.channel = ch
                # stragglers that failed into the outbox between the
                # flush and the swap drain on the next healthy _send
                # (opportunistic pre-send drain) — nothing is stranded
                # until "the next disconnect"
                from ray_tpu.util import events as events_mod

                events_mod.emit(
                    "INFO", events_mod.SOURCE_NODE,
                    f"re-registered with restarted head "
                    f"(epoch {self.epoch})", entity_id=self.node_hex)
                return True
            except Exception:
                try:
                    ch.close()
                except Exception:
                    pass
                time.sleep(0.3)
        return False

    def _handle(self, tag: str, payload) -> None:
        try:
            if tag == "dispatch":
                self.node.dispatch(pickle.loads(payload[0]), payload[1])
            elif tag == "dispatch_worker":
                wid, spec_b = payload
                spec = pickle.loads(spec_b)
                if not self.node.dispatch_to_worker(wid, spec):
                    self._send("dispatch_worker_failed", spec.task_id,
                               spec.actor_id)
            elif tag == "kill_worker":
                self.node.kill_worker(payload[0])
            elif tag == "cancel":
                self.node.cancel_task(*payload)
            elif tag == "store_delete":
                # honors in-flight holder leases (deferred until release)
                self.node.delete_from_store(payload[0])
            elif tag == "push_object":
                # broadcast-tree root op from the head
                oid, targets = payload
                threading.Thread(
                    target=self.node.push_object_to, args=(oid, targets),
                    daemon=True, name="bcast-root").start()
            elif tag == "store_info":
                # head asks for this node's store dump (memory_table):
                # bounded, read-only, replied one-way
                self._send("store_info_rep", payload[0],
                           self.node.store.object_infos())
            elif tag == "ping":
                # health probe (reference: gcs_health_check_manager.h) —
                # answered from the handler pool, so a wedged daemon
                # genuinely misses probes
                # the wall-clock echo feeds the head's min-RTT clock
                # offset estimator (flight-recorder trace merge); old
                # heads ignore the extra element
                self._send("pong", payload[0], time.time())
            elif tag == "stack_dump":
                # cluster stack dump: sampling blocks for duration_ms,
                # so it runs off the handler — pings must keep flowing
                # while this daemon profiles itself and its workers
                threading.Thread(
                    target=self._stack_dump, args=(payload[0], payload[1]),
                    daemon=True, name="stack-dump").start()
            elif tag == "cluster_view":
                # syncer broadcast (reference: RaySyncer RESOURCE_VIEW
                # fan-out); versioned — drop stale reorderings
                version, view = payload
                if version > self.cluster_view_version:
                    self.cluster_view_version = version
                    self.cluster_view = view
        except Exception:
            pass  # node dying; the head recovers via channel EOF

    def _stack_dump(self, req_id: int, duration_ms: int) -> None:
        """Sample this daemon + its workers, reply one-way. Best-effort:
        a missing reply just leaves this node absent from the dump (the
        head's collector has its own deadline)."""
        from ray_tpu.util import sampling_profiler

        stacks: dict = {}
        try:
            dur = max(0.0, duration_ms / 1000.0)
            stacks[f"{self.node.hex[:6]}:daemon"] = \
                sampling_profiler.collect_stacks(dur)
            stacks.update(self.node.collect_worker_stacks(dur))
        except Exception:
            pass  # partial dump beats none; reply what we have
        try:
            self._send("stack_rep", req_id, stacks)
        except Exception:
            pass  # node dying; the head's deadline covers it

    # ------------------------------------------- Head API consumed by Node

    def on_task_finished(self, node, task_id, err_name, spec, binding,
                         results, worker_id=None, attempt=None) -> None:
        self._send("task_finished", task_id, err_name,
                   pickle.dumps(spec) if spec is not None else None,
                   binding, results, worker_id, attempt)

    def on_object_sealed(self, oid: ObjectID, node_hex: str) -> None:
        self._send("sealed", oid)

    def publish_direct_events(self, node_hex: str, events) -> None:
        self._send("devents", events)

    def on_sealed_payload(self, oid: ObjectID, payload: bytes,
                          is_error: bool) -> None:
        self._send("sealed_payload", oid, payload, is_error)

    def on_stream_item(self, task_id, index: int) -> None:
        self._send("stream_item", task_id, index)

    def publish_oneway(self, channel: str, message) -> None:
        self._send("pub1", channel, message)

    def on_worker_metrics(self, source_id: str, snapshot: dict) -> None:
        self._send("worker_metrics", source_id, snapshot)

    def on_worker_spans(self, source_id: str, payload: dict) -> None:
        self._send("spans", source_id, payload)

    def record_cluster_events(self, events: list) -> None:
        self._send("cevents", events)

    def on_ref_report(self, source_id: str, table: dict) -> None:
        self._send("refs", source_id, table)

    def on_worker_log(self, node_hex: str, pid: int, text: str) -> None:
        self._send("worker_log", node_hex, pid, text)

    def on_worker_exit(self, node, w) -> None:
        self._send("worker_exit", w.worker_id, w.actor_id, w.pid)

    def on_worker_crashed(self, node, w, spec, binding, prev_state) -> None:
        self._send("worker_crashed", w.worker_id, w.actor_id, w.pid,
                   pickle.dumps(spec) if spec is not None else None,
                   binding, prev_state)

    def _bounded_rounds(self, make_req, done, timeout):
        """Re-issue a head request in <=2s rounds until ``done(result)`` or
        the deadline passes. An unbounded blocking request would pin one of
        the head's 16 daemon-request threads (pool starvation/deadlock)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            round_t = (2.0 if remaining is None
                       else max(0.0, min(remaining, 2.0)))
            result = self.rpc.call("req", *make_req(round_t),
                                   timeout=round_t + 30.0)
            if done(result) or (remaining is not None
                                and remaining <= round_t):
                return result

    def handle_worker_rpc(self, node, w, op: str, args):
        if op == "stream_next":
            task_id, index, timeout = args
            return self._bounded_rounds(
                lambda t: ("worker_rpc", ("stream_next", [task_id, index, t])),
                lambda rep: rep[0] != "wait", timeout)
        if op == "pg_ready":
            pg_id, timeout = args
            return self._bounded_rounds(
                lambda t: ("worker_rpc", ("pg_ready", [pg_id, t])),
                bool, timeout)
        return self.rpc.call("req", "worker_rpc", (op, list(args)))

    def wait_objects(self, oids, num_returns, timeout, fetch_local=False):
        if not fetch_local:
            return self._bounded_rounds(
                lambda t: ("wait_objects", (oids, num_returns, t)),
                lambda ready: len(ready) >= num_returns, timeout)
        # fetch_local on a daemon: ready = in THIS node's store; the wait
        # pulls cluster-available objects down as they appear. Small
        # objects arrive INLINE (never stored by the pull path), so track
        # them in a fetched set — store.contains alone would re-pull them
        # forever.
        deadline = None if timeout is None else time.monotonic() + timeout
        node = self.node
        fetched: set = set()
        while True:
            ready = [o for o in oids
                     if o in fetched or node.store.contains(o)]
            if len(ready) >= num_returns:
                return ready[:num_returns]
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                # budget exhausted: kick one ASYNC pull round for the
                # stragglers so a timeout=0 fetch_local wait still
                # STARTS transfers (the head-side wait spawns pulls the
                # same way; iterator prefetch relies on the side effect)
                self._spawn_prefetch([o for o in oids if o not in ready])
                return ready
            round_t = (2.0 if remaining is None
                       else max(0.05, min(remaining, 2.0)))
            missing = [o for o in oids if o not in ready]
            avail = self.rpc.call(
                "req", "wait_objects", (missing, len(missing), round_t),
                timeout=round_t + 30.0)
            for oid in avail:
                if node.store.contains(oid):
                    continue
                # bounded pull; failures re-locate on the next round
                rep = self.get_object_for_node(node, oid, round_t)
                if rep[0] == "inline":
                    try:
                        node.store.put_inline(oid, rep[1], rep[2],
                                              transfer=True)
                    except Exception:
                        pass
                    fetched.add(oid)
                elif rep[0] == "arena":
                    fetched.add(oid)

    def _spawn_prefetch(self, oids) -> None:
        """Background locate+pull for a timeout=0 fetch_local wait —
        readiness was already answered; this only starts the transfers.
        One thread PER object (the window is small — prefetch_batches+1
        refs): a ref whose producing task hasn't finished must not
        head-of-line-block transfer of the refs behind it, and the
        cross-wait dedup below would otherwise pin the whole batch
        behind the straggler. Each thread gives its object a bounded
        locate budget, then clears its dedup entry so a later wait
        re-kicks it; failures are silent (the consumer's real get()
        re-locates)."""
        node = self.node
        if node is None or not oids:
            return
        with self._prefetch_lock:
            todo = [o for o in oids if o not in self._prefetching
                    and not node.store.contains(o)]
            self._prefetching.update(todo)

        def run(oid):
            try:
                if not node.store.contains(oid):
                    rep = self.get_object_for_node(node, oid, 5.0)
                    if rep and rep[0] == "inline":
                        node.store.put_inline(oid, rep[1], rep[2],
                                              transfer=True)
            except Exception:
                pass
            finally:
                with self._prefetch_lock:
                    self._prefetching.discard(oid)

        for oid in todo:
            threading.Thread(target=run, args=(oid,), daemon=True,
                             name="prefetch-pull").start()

    def get_object_for_node(self, node, oid: ObjectID, timeout,
                            hint: Optional[str] = None):
        """Local-store check, then head locate + direct pull from the source
        node's object server (reference: pull_manager.h chunked pull).

        ``hint`` (direct-path owner hint) short-circuits the head locate
        entirely: the daemon pulls straight from the hinted peer's object
        server found in the syncer-broadcast cluster view."""
        from .object_transfer import pull_object, pull_object_striped

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if node.store.contains(oid):
                info = node.store.entry_info(oid)
                if info is None:
                    payload, is_err = node.store.get_payload(oid)
                    return ("inline", bytes(payload), is_err)
                off, size, is_err = info
                return ("arena", off, size, is_err)
            if hint and hint != node.hex:
                addr = next((tuple(e["addr"]) for e in self.cluster_view
                             if e.get("hex") == hint and e.get("addr")),
                            None)
                hint = None  # one shot: failure falls to the locate loop
                if addr is not None:
                    res = pull_object(addr, self.cluster_key, oid,
                                      dest_store=node.store)
                    if res is not None:
                        body, is_err = res
                        if isinstance(body, tuple):
                            _, off, size = body
                            self.on_object_sealed(oid, node.hex)
                            return ("arena", off, size, is_err)
                        return ("inline", body, is_err)
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return ("timeout",)
            round_t = 2.0 if remaining is None else max(0.05, min(remaining, 2.0))
            try:
                rep = self.rpc.call("req", "locate", (oid, round_t),
                                    timeout=round_t + 30.0)
            except Exception:
                if self.stopped.is_set():
                    raise ObjectLostError(oid, "head link lost")
                continue
            if rep[0] == "inline":
                return ("inline", rep[1], rep[2])
            if rep[0] == "locs":
                if len(rep[1]) >= 2:
                    # multi-holder: striped parallel pull with per-stripe
                    # failover (falls back to serial pulls internally, so
                    # a None covers every holder). Peers that failed —
                    # even when failover succeeded — get their stale
                    # locations dropped so locate stops handing them out.
                    addr_to_hex = {tuple(a): h for h, a in rep[1]}
                    failed: list = []
                    res = pull_object_striped(
                        [addr for _h, addr in rep[1]], self.cluster_key,
                        oid, node.store, on_peer_failed=failed.append)
                    for a in failed:
                        src_hex = addr_to_hex.get(tuple(a))
                        if src_hex is None:
                            continue
                        try:
                            self.rpc.call("req", "drop_location",
                                          (oid, src_hex), timeout=10.0)
                        except Exception:
                            pass
                    if res is not None:
                        body, is_err = res
                        if isinstance(body, tuple):
                            _, off, size = body
                            self.on_object_sealed(oid, node.hex)
                            return ("arena", off, size, is_err)
                        return ("inline", body, is_err)
                    time.sleep(0.05)  # all holders failed: re-locate
                    continue
                all_stale = True
                for src_hex, addr in rep[1]:
                    res = pull_object(addr, self.cluster_key, oid,
                                      dest_store=node.store)
                    if res is None:
                        # evicted/source died: invalidate so locate doesn't
                        # return the same stale address forever
                        try:
                            self.rpc.call("req", "drop_location",
                                          (oid, src_hex), timeout=10.0)
                        except Exception:
                            pass
                        continue
                    all_stale = False
                    body, is_err = res
                    if isinstance(body, tuple):
                        _, off, size = body
                        self.on_object_sealed(oid, node.hex)
                        return ("arena", off, size, is_err)
                    return ("inline", body, is_err)
                if all_stale:
                    time.sleep(0.05)  # let reconstruction/retry make progress
            # timeout / stale locations: loop


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ray_tpu node daemon",
        description="Join a ray_tpu head as a separate-process node")
    ap.add_argument("--address", required=True, help="head host:port")
    ap.add_argument("--key", required=True, help="cluster auth key (hex)")
    ap.add_argument("--num-cpus", type=float, default=None)
    ap.add_argument("--num-tpus", type=float, default=None)
    ap.add_argument("--resources", default="{}", help="JSON resource dict")
    ap.add_argument("--labels", default="{}", help="JSON label dict")
    ap.add_argument("--session-dir", default=None)
    ap.add_argument("--node-ip", default=None,
                    help="routable IP to advertise for this node (default: "
                    "RAY_TPU_NODE_IP env, else the interface that reaches "
                    "the head)")
    args = ap.parse_args(argv)

    from .accelerators import detect_resources

    key = bytes.fromhex(args.key)
    resources = detect_resources(
        num_cpus=int(args.num_cpus) if args.num_cpus is not None else None,
        num_tpus=int(args.num_tpus) if args.num_tpus is not None else None,
        extra=json.loads(args.resources))
    labels = json.loads(args.labels)

    channel = connect(parse_address(args.address), key)
    channel.send("hello", {})
    tag, (welcome,) = channel.recv()
    assert tag == "welcome", tag
    from .protocol import check_protocol

    check_protocol(welcome)
    # adopt the head's config so scheduler/store thresholds agree cluster-wide
    set_global_config(Config.from_json(welcome["config"]))

    head = RemoteHead(channel, welcome, key,
                      address=parse_address(args.address))
    # this process's cluster events flush over the head link (one-way)
    from ray_tpu.util import events as events_mod

    cfg = global_config()
    events_mod.set_sink(head.record_cluster_events,
                        cfg.cluster_event_flush_ms / 1000.0)
    session_dir = args.session_dir or tempfile.mkdtemp(prefix="raytpu_node_")

    node_ip = args.node_ip or os.environ.get("RAY_TPU_NODE_IP")
    if not node_ip:
        from .protocol import infer_node_ip

        node_ip = infer_node_ip(parse_address(args.address)[0])

    from .node import Node

    node = Node(head, NodeID(bytes.fromhex(welcome["node_hex"])), resources,
                session_dir, labels, node_ip=node_ip)
    head.node = node
    server = node.start_object_server(key)
    # per-node dashboard agent (reference: dashboard/agent.py:26): logs,
    # metrics, profile trigger — head dashboard proxies /api/nodes/<hex>/*
    from ray_tpu.dashboard.agent import NodeAgent

    loopback = node_ip in ("127.0.0.1", "localhost")
    agent = NodeAgent(node, host="127.0.0.1" if loopback else "0.0.0.0")
    # retained on the proxy: re-registration after a head bounce resends
    # this payload (plus a replay snapshot) under the same node hex
    head.ready_payload = {
        "resources": resources,
        "labels": labels,
        "object_addr": list(server.address),
        "pid": os.getpid(),
        "agent_addr": [node_ip, agent.address[1]],
    }
    channel.send("node_ready", head.ready_payload)
    from .syncer import NodeSyncer

    syncer = NodeSyncer(head, node)
    # this daemon's own flight-recorder spans (net-ring waits run here)
    # drain to the head on the report cadence, one-way and droppable
    from ray_tpu.util import flight_recorder as _fr

    _fr.adopt_config(cfg)
    _fr.set_process_label("daemon")
    _fr.set_dump_dir(session_dir)
    if _fr.enabled():
        def _span_report_loop():
            period = max(0.25,
                         cfg.flight_recorder_report_interval_ms / 1000.0)
            src = f"{node.hex[:6]}:daemon"
            while not head.stopped.is_set():
                time.sleep(period)
                try:
                    pl = _fr.drain()
                    if pl is not None:
                        head.on_worker_spans(
                            src, dict(pl, node_hex=node.hex))
                except Exception:
                    pass

        threading.Thread(target=_span_report_loop, daemon=True,
                         name="flightrec-report").start()
    if cfg.device_telemetry_enabled:
        from ray_tpu.util.device_telemetry import (observe_jax_import,
                                                    start_device_telemetry)

        observe_jax_import()  # compile events from process start, not tick 1
        start_device_telemetry(node_hex=node.hex)
    try:
        head.stopped.wait()
    except KeyboardInterrupt:
        pass
    syncer.stop()
    node.shutdown()
    head.close()
    from .object_transfer import close_pool

    close_pool()  # drop pooled transfer connections with the node
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
