"""Worker <-> node wire protocol.

Analog of the reference's gRPC ``CoreWorkerService``/``NodeManagerService``
split, collapsed for a single-machine node: each worker process holds one
authenticated unix-socket connection to its node (``multiprocessing.connection``
framing, pickle payloads). Messages are tagged tuples; both ends run a reader
thread and dispatch by tag, so calls in both directions interleave freely
(needed for async actors and nested task submission — reference:
core_worker.proto:439 direct worker push).

Tags (worker -> node):
    register(worker_id, pid)        -- handshake
    done(task_id, results, err)     -- task finished; results inline or sealed
    store(req_id, op, *args)        -- blocking store ops (get/create/seal/..)
    rpc(req_id, op, *args)          -- control-plane ops (submit, actors, kv)

Tags (node -> worker):
    exec(task_payload)              -- run a task
    cancel(task_id)
    rep(req_id, ok, value)          -- reply to store/rpc
    shutdown()
"""

from __future__ import annotations

import itertools
import threading
from multiprocessing import connection as mpc
from typing import Any, Callable, Dict, Optional, Tuple

from .fault_injection import should_drop as _fault_should_drop


# Wire protocol version, carried in every welcome handshake (node daemon
# join, client-driver connect). Bump on any incompatible change to message
# tags/payload shapes — mixed-version clusters fail fast with a clear
# error instead of unpickling garbage (the pickle-schema analog of the
# reference's versioned protobuf wire format, src/ray/protobuf/).
PROTOCOL_VERSION = 12  # v12: cluster stack dump. ADDED the "stack"
# request (head -> worker/daemon: one bounded sampling-profiler round,
# duration_ms) and its one-way "stack_rep" reply (collapsed-stack text
# per process) behind `python -m ray_tpu stack` / GET /api/stacks.
# (v11: flight-recorder span plane. ADDED the
# one-way "spans" tag (worker/daemon -> head: drained flight-recorder
# ring payloads for the cluster timeline, util/flight_recorder.py) and
# EXTENDED the health-prober pong payload to (seq, wall_time) so the
# head can estimate per-host clock offsets (min-RTT midpoint) when
# merging traces.)
# (v10: zero-copy net-ring tensor bodies. ADDED
# "nrdv" (data-with-raw-body: header (nrdv, seq, tag, nbytes) followed
# by one raw mpc frame carrying the writev'd segment body; the serve
# loop reassembles the canonical "nrd" before the protocol state
# machine — see core/net_ring.py _net_send/send_segments).
# (v9: cross-host compiled-graph rings. ADDED the
# NetRing session ops (core/net_ring.py, the machine-checked
# ring-protocol-net transport): "nring" (writer hello naming a ring id),
# "nrd" (data: seq + tag + payload), "nra" (cumulative ack), "nrrq"
# (reader resync request), "nrbase" (resync reply carrying the writer's
# acked base).
# (v8: restartable head — daemon rejoin. ADDED
# head->daemon "reregister" (stale-epoch kick); the "hello" payload may
# carry {"rejoin": node_hex} (daemon re-registering after a head bounce
# keeps its hex), "welcome" carries the head epoch, "node_ready" may
# carry a replay snapshot (store manifest + holder leases + hosted
# actors), and syncer snapshots echo the epoch.
# v7: head-free actor plane — owner-side ref accounting and stream
# publication; DELETED head hot-path ops dpin/pin_delta/is_pinned/
# dspub/dseof/stream_pub_item/stream_pub_eof, ADDED stream_sub/ssub/
# srep/psub/psubrep. v6: dropped dead worker->node "release" tag.
# v5: memory observability — "refs" reports + store_info/store_info_rep.
# v4: pooled object transfer, stat/pullr. v3: ddone/pdone exec_hex)


class ProtocolVersionError(ConnectionError):
    def __init__(self, theirs, ours=PROTOCOL_VERSION):
        super().__init__(
            f"wire protocol mismatch: peer speaks v{theirs}, this process "
            f"speaks v{ours}; upgrade both sides to the same ray_tpu")


def check_protocol(welcome: dict) -> None:
    theirs = welcome.get("proto", 0)
    if theirs != PROTOCOL_VERSION:
        raise ProtocolVersionError(theirs)


class Channel:
    """Thread-safe duplex message channel over a multiprocessing Connection."""

    def __init__(self, conn):
        self.conn = conn
        self._send_lock = threading.Lock()
        self.closed = False

    def send(self, tag: str, *payload) -> None:
        # chaos harness: "wire.send.<tag>=drop@N" silently loses this
        # message, "...=delay:MS" stalls it (fault_injection.py); the
        # fast path when no spec is armed is one string compare
        if _fault_should_drop("wire.send", tag):
            return
        with self._send_lock:
            self.conn.send((tag, payload))

    def recv(self) -> Tuple[str, tuple]:
        return self.conn.recv()

    def close(self) -> None:
        self.closed = True
        # socket shutdown BEFORE close: a thread parked in recv(2) on
        # this connection is not interrupted by closing the fd (it would
        # sit there until the peer sends) — shutdown pops it with EOF
        # immediately, so reader threads can be joined at teardown
        try:
            import os as _os
            import socket as _socket

            s = _socket.socket(fileno=_os.dup(self.conn.fileno()))
            try:
                s.shutdown(_socket.SHUT_RDWR)
            finally:
                s.close()
        except (OSError, ValueError, AttributeError):
            pass  # not socket-backed / already closed
        try:
            self.conn.close()
        except OSError:
            pass


class RpcClient:
    """Request/reply layer over a Channel (used by workers toward the node)."""

    def __init__(self, channel: Channel):
        self.channel = channel
        self._counter = itertools.count()
        self._pending: Dict[int, "Future"] = {}
        self._lock = threading.Lock()

    def call(self, tag: str, op: str, *args, timeout: Optional[float] = None) -> Any:
        req_id = next(self._counter)
        fut = Future()
        with self._lock:
            self._pending[req_id] = fut
        self.channel.send(tag, req_id, op, *args)
        return fut.result(timeout)

    def handle_reply(self, req_id: int, ok: bool, value: Any) -> None:
        with self._lock:
            fut = self._pending.pop(req_id, None)
        if fut is None:
            return
        if ok:
            fut.set_result(value)
        else:
            fut.set_exception(value)

    def fail_all(self, exc: Exception) -> None:
        with self._lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            fut.set_exception(exc)


class Future:
    __slots__ = ("_event", "_value", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def set_result(self, v):
        self._value = v
        self._event.set()

    def set_exception(self, e):
        self._exc = e
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("rpc timeout")
        if self._exc is not None:
            raise self._exc
        return self._value


def make_listener(address, authkey: bytes) -> mpc.Listener:
    """Listener over a unix socket (str path) or TCP ((host, port) tuple).

    TCP is the multi-host transport — the analog of the reference's gRPC
    server sockets (src/ray/rpc/grpc_server.h); auth uses the
    multiprocessing HMAC challenge with the cluster key.
    """
    # backlog: mpc's default of 1 drops concurrent connects (prestarted
    # workers racing the accept-side handshake got ECONNREFUSED and died)
    if isinstance(address, str):
        return mpc.Listener(address=address, family="AF_UNIX",
                            backlog=64, authkey=authkey)
    return mpc.Listener(address=tuple(address), family="AF_INET",
                        backlog=64, authkey=authkey)


def close_listener(listener) -> None:
    """Close a Listener AND wake any thread parked in ``accept()``.

    A plain ``close()`` frees the fd but leaves a thread blocked in
    accept(2) parked forever (Linux does not interrupt the syscall), so
    a teardown path that joins its accept loop would wait out the full
    join timeout.  ``shutdown(SHUT_RDWR)`` on the listening socket pops
    accept with an error immediately (verified for AF_UNIX and
    AF_INET)."""
    import socket as _socket

    try:
        listener._listener._socket.shutdown(_socket.SHUT_RDWR)
    except (OSError, AttributeError):
        pass
    try:
        listener.close()
    except OSError:
        pass


def set_nodelay(conn) -> None:
    """Disable Nagle on a TCP multiprocessing Connection. The control
    planes exchange small request/reply messages; Nagle + delayed ACK
    adds tens of ms per round trip (measured: daemon-hosted actor calls
    at 81/s vs 2.5k/s over unix sockets before this)."""
    import socket

    try:
        s = socket.fromfd(conn.fileno(), socket.AF_INET,
                          socket.SOCK_STREAM)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.close()  # fromfd dup'd the fd; the option sticks to the socket
    except OSError:
        pass


def connect(address, authkey: bytes) -> Channel:
    if isinstance(address, str):
        return Channel(mpc.Client(address=address, family="AF_UNIX",
                                  authkey=authkey))
    conn = mpc.Client(address=tuple(address), family="AF_INET",
                      authkey=authkey)
    set_nodelay(conn)
    return Channel(conn)


def infer_node_ip(peer_host: str = "8.8.8.8") -> str:
    """IP of the local interface the kernel would route to ``peer_host``
    (reference: ``services.get_node_ip_address``). The UDP connect never
    sends a packet — it only selects the egress interface. Pass the head's
    host to get the address peers on that network can reach."""
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((peer_host, 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def parse_address(addr: str):
    """"host:port" -> (host, port); anything else is a unix-socket path."""
    if ":" in addr and not addr.startswith("/"):
        host, _, port = addr.rpartition(":")
        return (host, int(port))
    return addr
