"""Deterministic fault injection: the chaos harness's failure points.

Reference: the testing hooks the reference scatters through its C++ core
(``RAY_testing_asio_delay_us``, ray_config_def.h:821, and the
``RAY_testing_rpc_failure`` op-failure injection) — config-gated points
in production code paths that tests flip on to kill processes, drop or
delay specific wire messages, and crash at named places, WITHOUT
test-only forks of the logic under test.

Spec grammar (``RAY_TPU_TEST_FAULT_SPEC`` / ``Config.test_fault_spec``)::

    spec   := rule (';' rule)*
    rule   := point '=' action ('@' hit)?
    hit    := N        fire on the N-th hit of the point only (1-based)
            | N+       fire on every hit from the N-th on
    action := crash        hard process death (os._exit) at the point
            | raise        raise FaultInjected (surfaces as a task error)
            | drop         caller discards the message/op
            | fail         caller reports the op as failed without doing it
            | delay:MS     sleep MS milliseconds inline, then continue

Points are dotted names.  A ``fire(point, detail)`` call matches a rule
whose point is either the bare ``point`` or ``point.detail`` — so
``worker.exec=crash@2`` kills whichever worker executes the 2nd task in
that process, while ``worker.exec.boom=crash@1`` targets the first
execution of a function named ``boom``.  Hit counters are per-process
and per-rule-key, which is what makes a spec deterministic: the same
spec against the same workload kills the same operation every run.

The spec rides the normal Config snapshot, so daemons and workers adopt
the head's spec at registration — a single env var arms the whole
cluster.  Tests running in one process use :func:`configure` /
:func:`reset` directly.

Instrumented points (each one ``fire()`` call in production code):

    worker.exec[.<fn>]      worker_runtime._execute, before user code
    dag.exec[.<fn>]         compiled-graph exec loops, before each round
                            invokes its method (``crash`` = the replica-
                            death drill for the compiled serve plane)
    wire.send[.<tag>]       protocol.Channel.send (control-plane msgs)
    node.dispatch_worker    Node.dispatch_to_worker (``fail`` bounces
                            the dispatch as a dead-worker report)
    daemon.sync             NodeSyncer loop (``drop`` loses a snapshot)
    head.daemon_req[.<op>]  Head._handle_daemon_req
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple


class FaultInjected(RuntimeError):
    """Raised at a fault point armed with the ``raise`` action."""

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"fault injected at {point!r}")


class _Rule:
    __slots__ = ("point", "action", "arg", "start", "open_ended")

    def __init__(self, point: str, action: str, arg: float,
                 start: int, open_ended: bool):
        self.point = point
        self.action = action
        self.arg = arg
        self.start = start
        self.open_ended = open_ended

    def matches(self, hit: int) -> bool:
        return hit >= self.start if self.open_ended else hit == self.start


_ACTIONS = ("crash", "raise", "drop", "fail", "delay")

_lock = threading.Lock()
_spec_loaded: Optional[str] = None
_rules: Dict[str, List[_Rule]] = {}
_counts: Dict[str, int] = {}


def parse_spec(spec: str) -> Dict[str, List[_Rule]]:
    """Parse a fault spec; raises ValueError on malformed rules."""
    rules: Dict[str, List[_Rule]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"fault rule {part!r} missing '='")
        point, rhs = part.split("=", 1)
        point = point.strip()
        hit = "1+" if "@" not in rhs else rhs.split("@", 1)[1].strip()
        action = rhs.split("@", 1)[0].strip()
        arg = 0.0
        if action.startswith("delay:"):
            arg = float(action.split(":", 1)[1]) / 1000.0
            action = "delay"
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} in {part!r}")
        open_ended = hit.endswith("+")
        start = int(hit[:-1] if open_ended else hit)
        if start < 1:
            raise ValueError(f"fault hit index must be >= 1 in {part!r}")
        rules.setdefault(point, []).append(
            _Rule(point, action, arg, start, open_ended))
    return rules


def configure(spec: str) -> None:
    """Arm (or clear, with "") the process-local fault spec and reset
    hit counters. Tests use this directly; separate processes pick the
    spec up from Config (see :func:`_ensure_loaded`)."""
    global _spec_loaded, _rules
    with _lock:
        _rules = parse_spec(spec)
        _spec_loaded = spec
        _counts.clear()


def reset() -> None:
    configure("")


def hits(point: str) -> int:
    """Hit count for an armed point (test assertions)."""
    with _lock:
        return _counts.get(point, 0)


def _ensure_loaded() -> bool:
    """Sync the parsed rules with the current Config spec. Returns True
    when any rules are armed. The fast path (no spec anywhere) is one
    global read + string compare."""
    global _spec_loaded, _rules
    from .config import global_config

    spec = global_config().test_fault_spec
    if spec == _spec_loaded:
        return bool(_rules)
    with _lock:
        if spec != _spec_loaded:
            try:
                _rules = parse_spec(spec)
            except ValueError:
                _rules = {}
            _spec_loaded = spec
            _counts.clear()
    return bool(_rules)


def fire(point: str, detail: Optional[str] = None) -> Optional[str]:
    """Hit a fault point. Returns the matched action name for actions
    the CALLER must apply ("drop" / "fail"), applies inline actions
    (crash / raise / delay) directly, or returns None."""
    if not _ensure_loaded():
        return None
    keys: Tuple[str, ...] = (point,) if detail is None \
        else (point, f"{point}.{detail}")
    matched: Optional[_Rule] = None
    with _lock:
        for key in keys:
            rules = _rules.get(key)
            if not rules:
                continue
            n = _counts.get(key, 0) + 1
            _counts[key] = n
            for rule in rules:
                if rule.matches(n):
                    matched = rule
                    break
            if matched is not None:
                break
    if matched is None:
        return None
    if matched.action == "crash":
        # hard process death, as close to kill -9 as Python allows: no
        # atexit, no finally blocks, no flushes. The one exception is
        # the crash FLIGHT RECORDER: its whole job is a last-N-seconds
        # span dump at exactly this kind of death, written synchronously
        # here (bounded, best-effort) before the exit
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.dump(f"chaos:{matched.point}")
        except Exception:
            pass
        os._exit(13)
    if matched.action == "raise":
        raise FaultInjected(matched.point)
    if matched.action == "delay":
        time.sleep(matched.arg)
        return None
    return matched.action  # "drop" | "fail": caller applies


def should_drop(point: str, detail: Optional[str] = None) -> bool:
    """True when the caller must silently discard the message/op."""
    return fire(point, detail) == "drop"
