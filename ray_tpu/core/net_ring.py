"""Cross-host ring channels for compiled graphs (NetRing v1).

The shm rings in :mod:`ray_tpu.experimental.channel` are /dev/shm — both
endpoints must share a host.  This module is the cross-host data plane:
the SAME slot/seq ring discipline carried over an authenticated
message-passing session (``multiprocessing.connection`` over TCP, the
peer-mesh transport ``core/object_transfer.py`` uses), where messages —
unlike mmap stores — can be lost, duplicated, and reordered across
connection breaks, and an endpoint process can restart mid-protocol.

The protocol is NOT designed here.  It implements, rule for rule, the
machine-checked contract in ``ray_tpu/tools/lint/ring_model_net.py``
(lint check id ``ring-protocol-net``, exhaustively explored for
``n_slots ∈ {1, 2}`` under loss + duplication + reordering + one
crash-restart, every guard mutation-tested):

- **Send window** — the writer produces only while ``w - acked <
  n_slots``; unacked payloads are retained in ``_unacked`` (the net
  analog of ring slots) until acknowledged, so a data message can never
  overwrite an unconsumed slot.
- **Slot stamping + seq cross-check** — a data message ``(nrd, seq, …)``
  stamps receive slot ``(seq-1) % n_slots``; the reader consumes
  strictly in seq order and cross-checks the stamped seq against
  ``r + 1`` exactly like the shm per-slot header check.
- **Cumulative acks, folded by max()** — the reader acks ``(nra, r)``
  after every consume; stale/reordered/duplicated acks are harmless.
- **Go-Back-N re-ack** — a data message outside ``r < seq <= r +
  n_slots`` is dropped AND re-acked with the cumulative ack.  The
  re-ack is load-bearing: a lost final ack would otherwise pin the
  writer's window shut forever (the wedge the spec's model checker
  caught in its first draft).
- **Retransmit** — the writer re-sends ``acked + 1`` whenever an
  unacked message exists and no ack progress was observed for a
  retransmit interval (and immediately after a reconnect).  Retransmit
  + re-ack also heal a writer-session restart with no handshake:
  ``acked`` is a session-volatile cache that rebuilds from re-acks.
- **Hybrid park/wake** — bounded spin, then raise the own parked flag,
  RECHECK the condition, sleep; a delivery (the network doorbell) rings
  the parked side iff its flag is up.  Here the flag/recheck/sleep
  sequence runs under the endpoint's condition lock, which is strictly
  stronger than the model's interleaving (the model proves the
  lock-free ordering; the lock can only remove interleavings).
- **Reader-only resync** — a reader attaching without a cursor sends
  ``(nrrq)``; the writer answers ``(nrbase, acked)`` and the reader
  adopts ``r = acked`` (delivery degrades to at-least-once across a
  reader restart — the DAG layer's seq-tagged results make
  re-execution idempotent).  In the compiled-graph integration a
  restarted executor gets FRESH rings at rebind, so resync is the
  transport-level recovery path (same-ring reader re-attach), kept
  conformant to the spec and exercised by the conformance tests.

Wire session (one duplex authenticated connection per edge, writer
dials the reader process's :class:`NetRingHost` listener):

    writer -> host:   ("nring", ring_id)          attach to the ring
    writer -> reader: ("nrd", seq, tag, payload)  data (seq from 1)
                      ("nrdv", seq, tag, nbytes)  data header; the next
                                                  frame is the raw
                                                  writev'd segment body
                                                  (tensor zero-copy)
                      ("nrbase", acked)           resync reply
    reader -> writer: ("nra", r)                  cumulative ack
                      ("nrrq",)                   resync request

Every send passes the ``wire.send.<tag>`` chaos point
(``RAY_TPU_TEST_FAULT_SPEC``: ``wire.send.nra=drop@3`` loses the 3rd
ack, ``wire.send.nrd=delay:50`` stalls data), so the fault harness can
drive exactly the loss cases the model checker proved recoverable.

The endpoints expose the same channel API the shm rings present
(``wait_writable`` / ``write`` / ``write_serialized`` / ``write_array``
/ ``read`` / ``occupancy`` / ``close``), so the compiled-graph layer
picks shm or net per edge without the driver or executor loops caring.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ray_tpu.experimental.channel import (
    RETRANSMITS,
    STALLS,
    STATS,
    TAG_BYTES,
    TAG_DATA,
    TAG_ERROR,
    TAG_STOP,
    TAG_STREAM,
    TAG_TENSOR,
    ChannelClosed,
    ChannelTimeout,
    _maybe_flush,
    _sp_park,
    _sp_wait_read,
    _sp_wait_write,
    tensor_payload,
    parse_tensor,
)
from ray_tpu.util import flight_recorder as _fr

# net-side retransmission instants (the shared RETRANSMITS counter cell
# feeds the registry; the span gives each event a timeline position)
_sp_retransmit = _fr.register_span("net.retransmit", tag_keys=("channel",))

from .fault_injection import should_drop as _fault_should_drop

# wait tuning: bounded optimistic spin before parking on the condition
# (data arrives on the rx thread within ~50-100us on a hot LAN edge;
# parking costs a futex round trip per message)
_SPIN_ITERS = 1000


class _Segments(tuple):
    """A tensor payload kept as its framed segments — (len-prefix, meta,
    raw buffer view) — all the way to the socket write.

    ``_LockedSend.send_segments`` writevs the segments straight into
    the connection as one mpc-framed body, so NO joined intermediate
    copy of the tensor ever exists on the send path (the shm rings'
    pack-into-the-slot equivalent for TCP). Instances sit in
    ``_unacked`` as-is for retransmission: the segments are VIEWS of
    the produced array, retained until acked per the durable-slot
    contract — which makes ``write_array`` an ownership transfer
    (MPI_Isend semantics): the caller must not mutate the array until
    it is acked, or a retransmit after a session break/stall ships the
    mutated bytes. The compiled-graph producers honor this by
    construction — jax arrays are immutable and each execution
    produces fresh numpy results; a caller recycling one host buffer
    must copy before writing."""

    __slots__ = ()

    @property
    def total(self) -> int:
        return sum(len(s) for s in self)

    def join(self) -> bytes:
        """Materialize (the non-writev fallback); counted as a copy."""
        STATS["tensor_copy_bytes"] += self.total
        return b"".join(bytes(s) if not isinstance(s, bytes) else s
                        for s in self)


def _writev_all(fd, buffers) -> None:
    """``os.writev`` the buffer list fully (blocking fd): partial writes
    advance across segment boundaries without re-buffering."""
    bufs = [memoryview(b).cast("B") for b in buffers if len(b)]
    while bufs:
        n = os.writev(fd, bufs)
        while bufs and n >= len(bufs[0]):
            n -= len(bufs[0])
            bufs.pop(0)
        if n and bufs:
            bufs[0] = bufs[0][n:]


class _LockedSend:
    """Serialize sends on one duplex connection: the consume thread's
    acks and the serve/rx thread's protocol replies share the socket,
    and ``multiprocessing.connection`` framing is not thread-safe."""

    __slots__ = ("_conn", "_lock")

    def __init__(self, conn):
        self._conn = conn
        self._lock = threading.Lock()

    def __call__(self, msg) -> None:
        # deliberate: this lock exists ONLY to serialize the socket
        # write and is a leaf — no other lock is ever taken under it,
        # and it is never held across anything but this one send
        with self._lock:
            self._conn.send(msg)  # graftlint: ignore[blocking-under-lock]

    def send_segments(self, header_msg, segments: _Segments) -> None:
        """Two frames under one lock hold: the pickled header tuple,
        then the segments writev'd as a single raw mpc-framed body
        (``!i`` length prefix — same framing ``Connection.send_bytes``
        emits, so the peer's ``recv_bytes`` reads it back verbatim).
        The lock keeps the frame pair adjacent on the stream."""
        import struct

        total = segments.total
        if total > 0x7FFFFFFF:  # mpc large-frame pre-header territory
            raise ValueError(f"segment body of {total}B exceeds the "
                             f"single-frame limit")
        frame = [struct.pack("!i", total)] + list(segments)
        with self._lock:
            self._conn.send(header_msg)  # graftlint: ignore[blocking-under-lock]
            _writev_all(self._conn.fileno(), frame)  # graftlint: ignore[blocking-under-lock]


def _net_send(send, tag: str, *payload) -> bool:
    """Send one net-ring message through ``send`` with the chaos
    wire-point applied. Returns False when the message was dropped (by
    injection or a broken session) — callers never raise: the protocol
    recovers every loss via retransmit/re-ack.

    A data message whose payload is a :class:`_Segments` rides the
    writev path when the session sender supports it: the wire carries
    ``("nrdv", seq, tag, nbytes)`` followed by the raw framed body (the
    serve loop reassembles ``("nrd", seq, tag, body)`` before applying
    it, so the protocol state machine sees one identical "nrd" either
    way — the chaos point is likewise keyed "nrd" for both spellings).
    Senders without a socket (model-conformance harnesses, scripted
    traces) fall back to joining — the copy the counter then records."""
    if _fault_should_drop("wire.send", tag):
        return False
    try:
        if payload and isinstance(payload[-1], _Segments):
            body = payload[-1]
            if tag == "nrd" and hasattr(send, "send_segments"):
                send.send_segments(
                    ("nrdv",) + payload[:-1] + (body.total,), body)
                return True
            send((tag,) + payload[:-1] + (body.join(),))
            return True
        send((tag,) + payload)
        return True
    except Exception:
        return False  # session broke mid-send: reconnect + retransmit


class _Endpoint:
    """State + park/wake shared by both ring ends."""

    def __init__(self, ring_id: str, n_slots: int, capacity: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.ring_id = ring_id
        self.n_slots = n_slots
        self.capacity = capacity
        self.path = f"net:{ring_id}"  # error messages parity with shm
        base = ring_id.split("_", 1)[-1] if "_" in ring_id else ring_id
        self._metric_name = f"net:{base}"
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.parked = 0  # the model's wflag/rflag (wake elision)
        self._closed: Optional[BaseException] = None
        self._send: Optional[Callable] = None  # attached session send

    def attach_send(self, send: Optional[Callable]) -> None:
        with self._lock:
            self._send = send

    # set by each concrete end: which side of the ring stalls here
    _wait_role = "read"

    def _wait(self, ready, timeout: Optional[float]) -> None:
        """Hybrid wait for ``ready()`` (called under no lock): bounded
        spin, then flag-RECHECK-sleep under the condition lock — the
        delivering rx thread notifies iff the flag is up."""
        if ready():
            return
        # real wait: time it for the stall counter + flight-rec span
        # (shared dicts with the shm channel layer — one flush path)
        t0 = time.monotonic()
        try:
            self._wait_slow(ready, timeout)
        finally:
            dur = time.monotonic() - t0
            key = (self._metric_name, self._wait_role)
            STALLS[key] = STALLS.get(key, 0.0) + dur
            (_sp_wait_write if self._wait_role == "write"
             else _sp_wait_read).end_at(t0, dur, self._metric_name)

    def _wait_slow(self, ready, timeout: Optional[float]) -> None:
        for i in range(_SPIN_ITERS):
            if ready():
                return
            if i & 7 == 7:
                os.sched_yield()
        _sp_park.instant(self._metric_name, self._wait_role)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed is not None:
                    raise ChannelClosed(self.path) from self._closed
                self.parked = 1
                try:
                    if ready():
                        return
                    remaining = 0.2 if deadline is None else min(
                        0.2, deadline - time.monotonic())
                    if remaining <= 0:
                        raise ChannelTimeout(self.path)
                    self._cv.wait(remaining)
                finally:
                    self.parked = 0

    def _ring_bell(self) -> None:
        """Wake a parked peer thread (call under self._lock)."""
        if self.parked:
            self._cv.notify_all()

    def poison(self, cause: Optional[BaseException] = None) -> None:
        """Fail every current and future wait with ChannelClosed (the
        death-path analog of the shm STOP sentinel: a dead peer's ring
        has no live writer, so the local end unwedges itself)."""
        with self._cv:
            if self._closed is None:
                self._closed = cause or ChannelClosed(self.path)
            self._cv.notify_all()

    def occupancy(self) -> int:
        raise NotImplementedError

    def _check_closed(self) -> None:
        if self._closed is not None:
            raise ChannelClosed(self.path) from self._closed


class NetRingWriter(_Endpoint):
    """Producing end: owns ``w`` and the unacked payload window.

    ``_unacked`` retains every produced payload until the cumulative ack
    covers it — the durable-slot contract the model's writer-restart
    recovery relies on. ``acked`` is a session-volatile cache rebuilt
    from (re-)acks."""

    _wait_role = "write"

    def __init__(self, ring_id: str, n_slots: int, capacity: int,
                 send: Optional[Callable] = None):
        super().__init__(ring_id, n_slots, capacity)
        self.w = 0
        self.acked = 0
        self._unacked: Dict[int, Tuple[int, bytes]] = {}  # seq -> (tag, b)
        self._send = send
        self._last_acked_seen = 0
        # TCP session machinery (None in harness/conformance mode)
        self._conn = None
        self._conn_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = []

    # ---- protocol state ----

    def writable(self) -> bool:
        return self.w - self.acked < self.n_slots

    def occupancy(self) -> int:
        return self.w - self.acked

    def wait_writable(self, timeout: Optional[float] = None) -> None:
        """Block until the send window is open WITHOUT producing. A
        window observed open stays open until this (single-writer)
        thread produces — acks only widen it — so multi-edge input
        rounds stay all-or-nothing exactly as with shm rings."""
        self._check_closed()
        self._wait(self.writable, timeout)

    def produce(self, payload: bytes, tag: int = TAG_DATA) -> int:
        """Window-checked produce + send (the model's ``w:produce``).
        Callers must have observed the window open (wait_writable)."""
        with self._lock:
            self._check_closed()
            if not self.writable():
                raise ChannelTimeout(
                    f"{self.path}: send window closed (w={self.w} "
                    f"acked={self.acked} n_slots={self.n_slots})")
            self.w += 1
            seq = self.w
            self._unacked[seq] = (tag, payload)
            send = self._send
        if send is not None:
            _net_send(send, "nrd", seq, tag, payload)
        STATS["messages"] += 1
        _maybe_flush(self)
        return seq

    # ---- channel API (shm parity) ----

    def write(self, payload: bytes, tag: int = TAG_DATA,
              timeout: Optional[float] = None) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"message of {len(payload)}B exceeds channel slot capacity "
                f"{self.capacity}B (raise buffer_size_bytes)")
        self._wait(self.writable, timeout)
        self.produce(bytes(payload), tag)
        if tag == TAG_DATA or tag == TAG_ERROR:
            STATS["serialized_bytes"] += len(payload)
        elif tag == TAG_BYTES or tag == TAG_STREAM:
            STATS["raw_bytes"] += len(payload)

    def write_serialized(self, sobj, timeout: Optional[float] = None) -> None:
        total = sobj.total_bytes
        if total > self.capacity:
            raise ValueError(
                f"message of {total}B exceeds channel slot capacity "
                f"{self.capacity}B (raise buffer_size_bytes)")
        self._wait(self.writable, timeout)
        self.produce(sobj.to_bytes(), TAG_DATA)
        STATS["serialized_bytes"] += total

    def write_array(self, arr, timeout: Optional[float] = None) -> None:
        """Typed-tensor path: same wire format as the shm TENSOR slots
        ([meta_len][meta][raw]) and no OBJECT serializer on either end.
        The payload stays a :class:`_Segments` (prefix, meta, raw view)
        all the way to the socket, where the session sender writevs the
        framed body — zero full-tensor copies between the produced
        array and the TCP stream (``STATS["tensor_copy_bytes"]``
        asserts it; the pre-writev code paid one copy joining the
        segments and a second pickling the joined payload).

        Zero-copy contract: the array is borrowed until acked (the
        retransmit buffer holds views, not a snapshot — see
        :class:`_Segments`). Don't mutate a numpy ``arr`` after
        writing; pass a copy if the buffer is recycled."""
        meta, raw = tensor_payload(arr)
        payload = _Segments((len(meta).to_bytes(4, "little"), meta,
                             memoryview(raw)))
        if payload.total > self.capacity:
            raise ValueError(
                f"message of {payload.total}B exceeds channel slot "
                f"capacity {self.capacity}B (raise buffer_size_bytes)")
        self._wait(self.writable, timeout)
        self.produce(payload, TAG_TENSOR)
        STATS["tensor_bytes"] += raw.nbytes

    # ---- deliveries (writer side of the session) ----

    def on_message(self, msg: tuple,
                   reply: Optional[Callable] = None) -> None:
        """Apply one reader->writer message (the model's ack-channel
        delivery). ``reply`` sends back toward the reader (resync)."""
        kind = msg[0]
        if kind == "nra":
            with self._lock:
                new_acked = max(self.acked, msg[1])
                progressed = new_acked > self.acked
                self.acked = new_acked
                if progressed:
                    for seq in [s for s in self._unacked
                                if s <= new_acked]:
                        del self._unacked[seq]
                    self._ring_bell()
        elif kind == "nrrq":
            # reader resync request: answer with the retained-base seq
            with self._lock:
                base = self.acked
            if reply is not None:
                _net_send(reply, "nrbase", base)

    def retransmit_once(self) -> bool:
        """Re-send ``acked + 1`` while anything is unacked (the model's
        ``w:retransmit``; cumulative-ack Go-Back-N). When the payload
        for that seq is already freed — a restarted writer session
        whose pre-crash acks covered it — send a zero-length PROBE with
        the same seq: the reader's window check classifies it stale and
        answers the cumulative re-ack, which is all a freed seq is ever
        retransmitted for (a stale message's payload is never consumed;
        this is how ``acked`` rebuilds with no handshake)."""
        with self._lock:
            if self.acked >= self.w:
                return False
            seq = self.acked + 1
            tag, payload = self._unacked.get(seq, (TAG_DATA, b""))
            send = self._send
        if send is None:
            return False
        RETRANSMITS[0] += 1
        _sp_retransmit.instant(self._metric_name)
        return _net_send(send, "nrd", seq, tag, payload)

    # ---- TCP session ----

    @classmethod
    def connect(cls, address, authkey: bytes, ring_id: str,
                n_slots: int, capacity: int) -> "NetRingWriter":
        """Dial the reader process's NetRingHost and keep the session
        alive: a broken connection re-dials with backoff, and the
        retransmit timer re-covers whatever the gap lost."""
        self = cls(ring_id, n_slots, capacity)
        self._address = tuple(address)
        self._authkey = authkey
        self._dial()  # first connect synchronous: surface bad addresses
        t_rx = threading.Thread(target=self._rx_loop, daemon=True,
                                name=f"nring-w-rx-{ring_id[:12]}")
        t_rt = threading.Thread(target=self._retransmit_loop, daemon=True,
                                name=f"nring-w-rt-{ring_id[:12]}")
        self._threads = [t_rx, t_rt]
        for t in self._threads:
            t.start()
        return self

    def _dial(self) -> None:
        from multiprocessing import connection as mpc

        from .object_transfer import _tune_conn

        conn = mpc.Client(address=self._address, family="AF_INET",
                          authkey=self._authkey)
        _tune_conn(conn)
        conn.send(("nring", self.ring_id))
        with self._conn_lock:
            self._conn = conn
        self.attach_send(_LockedSend(conn))

    def _rx_loop(self) -> None:
        """Session thread: deliver acks; on EOF re-dial until closed.
        Runs the reconnect too, so there is exactly one thread touching
        the connection lifecycle."""
        backoff = 0.05
        while not self._stop.is_set():
            with self._conn_lock:
                conn = self._conn
            if conn is None:
                try:
                    self._dial()
                    backoff = 0.05
                except Exception:
                    self._stop.wait(backoff)
                    backoff = min(backoff * 2, 2.0)
                continue
            try:
                msg = conn.recv()
            except Exception:
                # peer gone or conn shut down: drop the session; the
                # retransmit timer re-covers the unacked window after
                # the re-dial
                self.attach_send(None)
                with self._conn_lock:
                    if self._conn is conn:
                        self._conn = None
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                with self._lock:
                    reply = self._send  # the session's locked sender
                self.on_message(msg, reply=reply)
            except Exception:
                pass  # malformed message: the protocol state is untouched

    def _retransmit_loop(self) -> None:
        from .config import global_config

        interval = max(0.005,
                       global_config().net_ring_retransmit_ms / 1000.0)
        while not self._stop.wait(interval):
            with self._lock:
                acked, w = self.acked, self.w
                stale = acked == self._last_acked_seen
                self._last_acked_seen = acked
            if acked < w and stale:
                self.retransmit_once()

    def close(self, unlink: bool = False) -> None:
        self._stop.set()
        self.poison()
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            try:  # shutdown pops a parked recv immediately (EOF)
                import socket as _socket

                s = _socket.socket(fileno=os.dup(conn.fileno()))
                try:
                    s.shutdown(_socket.SHUT_RDWR)
                finally:
                    s.close()
            except (OSError, ValueError):
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []


class NetRingReader(_Endpoint):
    """Consuming end: owns ``r`` and the receive ring.

    Created with ``resync=True`` when attaching to a ring whose writer
    may hold state from a previous reader session: consumption defers
    until the ``nrrq``/``nrbase`` handshake adopts ``r = acked``."""

    def __init__(self, ring_id: str, n_slots: int, capacity: int,
                 resync: bool = False):
        super().__init__(ring_id, n_slots, capacity)
        self.r = 0
        self._slots = [None] * n_slots  # (seq, tag, payload) | None
        self.resyncing = resync  # the model's RESYNC pc

    # ---- protocol state ----

    def readable(self) -> bool:
        if self.resyncing:
            return False
        slot = self._slots[self.r % self.n_slots]
        return slot is not None and slot[0] == self.r + 1

    def occupancy(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def start_resync(self) -> None:
        """Send the resync request (the model's ``r:resync-send``);
        at-least-once — re-sent on every session attach while still
        resyncing."""
        with self._lock:
            send = self._send if self.resyncing else None
        if send is not None:
            _net_send(send, "nrrq")

    # ---- deliveries (reader side of the session) ----

    def on_message(self, msg: tuple,
                   reply: Optional[Callable] = None) -> None:
        """Apply one writer->reader message (the model's data-channel
        delivery). ``reply`` sends back toward the writer (acks)."""
        kind = msg[0]
        if kind == "nrd":
            seq = msg[1]
            reack = None
            with self._lock:
                if self.resyncing:
                    # no cursor yet: drop; retransmission re-covers the
                    # unacked window once resync completes
                    return
                if not (self.r < seq <= self.r + self.n_slots):
                    # stale/zombie seq: Go-Back-N re-ack so a lost final
                    # ack cannot pin the writer's window shut
                    reack = self.r
                else:
                    self._slots[(seq - 1) % self.n_slots] = \
                        (seq, msg[2], msg[3])
                    self._ring_bell()
            if reack is not None and reply is not None:
                _net_send(reply, "nra", reack)
        elif kind == "nrbase":
            with self._lock:
                if self.resyncing:
                    self.r = msg[1]
                    self.resyncing = False
                    self._ring_bell()
            # else: stale resync reply — ignore

    # ---- channel API (shm parity) ----

    def consume(self) -> Tuple[int, bytes]:
        """In-order consume with the per-slot seq cross-check; sends the
        cumulative ack. Callers must have observed ``readable()``."""
        with self._lock:
            self._check_closed()
            idx = self.r % self.n_slots
            slot = self._slots[idx]
            if slot is None:
                raise ChannelTimeout(f"{self.path}: nothing readable")
            seq, tag, payload = slot
            if seq != self.r + 1:  # torn/stale stamp: protocol violation
                raise ChannelClosed(
                    f"{self.path}: slot seq {seq} != expected {self.r + 1}")
            self._slots[idx] = None
            self.r += 1
            r = self.r
            send = self._send
        if send is not None:
            _net_send(send, "nra", r)
        return tag, payload

    def read(self, timeout: Optional[float] = None,
             to_device: bool = False):
        self._wait(self.readable, timeout)
        tag, payload = self.consume()
        _maybe_flush(self)
        if tag == TAG_STOP:
            raise ChannelClosed(self.path)
        if tag == TAG_TENSOR:
            return (TAG_TENSOR, parse_tensor(payload, 0, to_device))
        return (tag, payload) if tag in (TAG_ERROR, TAG_BYTES, TAG_STREAM) \
            else (TAG_DATA, payload)

    def close(self, unlink: bool = False) -> None:
        self.poison()
        host = _host_singleton[0]
        if host is not None:
            host.unregister(self.ring_id)


class NetRingHost:
    """Per-process listener the reading side of every net ring shares.

    One authenticated TCP listener per process; writers dial it, name a
    ring id in their hello, and the per-connection serve thread becomes
    that ring's delivery thread.  The listener key is minted per process
    and travels only inside already-authenticated actor-call payloads
    (the compile-time handshake), so ring sessions inherit the cluster's
    trust boundary without a shared global key."""

    def __init__(self, advertise_ip: str = "127.0.0.1"):
        from multiprocessing import connection as mpc

        self.authkey = os.urandom(24)
        self._listener = mpc.Listener(address=("0.0.0.0", 0),
                                      family="AF_INET", authkey=self.authkey)
        _bound_host, self.port = self._listener.address
        self.advertise_ip = advertise_ip or "127.0.0.1"
        self._rings: Dict[str, NetRingReader] = {}
        self._lock = threading.Lock()
        self._alive = True
        self._conns: set = set()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="nring-host-accept")
        self._accept_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        """Dial-in computed at READ time: the advertise ip can be
        corrected after the host started (a worker learns its real
        node ip via a control message that may land after the first
        ring was created — a host pinned to the init-default loopback
        would hand unroutable addresses to remote writers forever)."""
        return (self.advertise_ip, self.port)

    # ---- registry ----

    def register(self, reader: NetRingReader) -> None:
        with self._lock:
            self._rings[reader.ring_id] = reader

    def unregister(self, ring_id: str) -> None:
        with self._lock:
            self._rings.pop(ring_id, None)

    def get(self, ring_id: str) -> Optional[NetRingReader]:
        with self._lock:
            return self._rings.get(ring_id)

    def poison_prefix(self, prefix: str) -> int:
        """Poison every registered reader whose ring id starts with
        ``prefix`` (a compiled DAG's uid): the death path for stages
        downstream of a dead peer — their parked reads pop with
        ChannelClosed instead of waiting on a corpse."""
        with self._lock:
            victims = [rd for rid, rd in self._rings.items()
                       if rid.startswith(prefix)]
        for rd in victims:
            rd.poison()
        return len(victims)

    # ---- serving ----

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn = self._listener.accept()
            except Exception:
                if not self._alive:
                    return
                continue
            if not self._alive:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            from .object_transfer import _tune_conn

            _tune_conn(conn)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,), daemon=True,
                             name="nring-host-serve").start()

    def _serve(self, conn) -> None:
        """Per-session delivery thread: hello, then every received
        message is applied to the ring's reader; the reader's acks ride
        the same duplex connection."""
        reader = None
        my_send = None
        try:
            hello = conn.recv()
            op = hello[0] if isinstance(hello, tuple) and hello else None
            if op == "nring":
                reader = self.get(hello[1])
            if reader is None:
                return  # bad hello / unknown ring: writer re-dials
            my_send = _LockedSend(conn)
            reader.attach_send(my_send)
            # a reader awaiting resync asks on every session attach
            # (at-least-once; stale extra nrrq answers are idempotent)
            reader.start_resync()
            while self._alive:
                msg = conn.recv()
                if (isinstance(msg, tuple) and msg
                        and msg[0] == "nrdv"):
                    # writev'd data: the header frame names the body
                    # length; the next frame on this connection IS the
                    # raw body (the sender holds its lock across the
                    # pair, so no frame interleaves). Reassembled into
                    # the canonical "nrd" before the state machine.
                    body = conn.recv_bytes()
                    msg = ("nrd", msg[1], msg[2], body)
                reader.on_message(msg, reply=my_send)
        except (EOFError, OSError, TypeError, ValueError):
            pass  # session over: writer re-dials and retransmits
        finally:
            if reader is not None:
                with reader._lock:
                    # only clear OUR session: a reconnected writer may
                    # already have attached a fresh sender
                    if reader._send is my_send:
                        reader._send = None
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._alive = False
        from .protocol import close_listener

        close_listener(self._listener)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
            rings = list(self._rings.values())
            self._rings.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        for rd in rings:
            rd.poison()
        self._accept_thread.join(timeout=2.0)


# Process-wide host: every reading endpoint in a process shares one
# listener; the compiled-graph setup RPC returns (address, key) so the
# writing processes can dial it.
_host_singleton: list = [None]
_host_lock = threading.Lock()


def ensure_host(advertise_ip: Optional[str] = None) -> NetRingHost:
    host = _host_singleton[0]
    if host is None or not host._alive:
        with _host_lock:
            host = _host_singleton[0]
            if host is None or not host._alive:
                host = NetRingHost(advertise_ip or "127.0.0.1")
                _host_singleton[0] = host
    # callers pass the CURRENT node ip: adopt a late-arriving real
    # address over the loopback default (never the reverse)
    if advertise_ip and advertise_ip != "127.0.0.1":
        host.advertise_ip = advertise_ip
    return host


def create_reader(ring_id: str, n_slots: int, capacity: int,
                  advertise_ip: Optional[str] = None,
                  resync: bool = False) -> NetRingReader:
    """Create + register the reading end of a ring in this process;
    returns the reader. The host's (address, authkey) — what a writer
    needs to dial in — comes from :func:`ensure_host`."""
    host = ensure_host(advertise_ip)
    reader = NetRingReader(ring_id, n_slots, capacity, resync=resync)
    host.register(reader)
    return reader


def poison_rings(prefix: str) -> int:
    """Poison this process's net-ring readers under a DAG uid (driver
    death-path broadcast; no-op when the process hosts none)."""
    host = _host_singleton[0]
    if host is None:
        return 0
    return host.poison_prefix(prefix)
