"""Unique identifiers for tasks, objects, actors, nodes, jobs, placement groups.

TPU-native analog of the reference's ``src/ray/common/id.h`` ID hierarchy:
fixed-width random IDs with cheap hashing and hex round-trip. Unlike the
reference (which derives ObjectIDs from TaskID + return index in C++), we keep
the same *derivation scheme* but implement it with Python ``os.urandom`` /
``hashlib`` — the IDs only need to be unique within a cluster session.
"""

from __future__ import annotations

import hashlib
import os
import threading


class BaseID:
    """Fixed-size binary id with hex repr. Subclasses set SIZE and PREFIX."""

    SIZE = 16
    PREFIX = "id"
    __slots__ = ("_bin", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bin = binary
        self._hash = hash(binary)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __lt__(self, other):
        return self._bin < other._bin

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    SIZE = 4
    PREFIX = "job"


class NodeID(BaseID):
    SIZE = 16
    PREFIX = "node"


class WorkerID(BaseID):
    SIZE = 16
    PREFIX = "worker"


class ActorID(BaseID):
    SIZE = 16
    PREFIX = "actor"


class PlacementGroupID(BaseID):
    SIZE = 16
    PREFIX = "pg"


class TaskID(BaseID):
    SIZE = 16
    PREFIX = "task"

    _counter_lock = threading.Lock()
    _counter = 0

    @classmethod
    def for_driver_task(cls, job_id: JobID):
        with cls._counter_lock:
            cls._counter += 1
            n = cls._counter
        h = hashlib.blake2b(
            job_id.binary() + n.to_bytes(8, "little"), digest_size=cls.SIZE
        )
        return cls(h.digest())


class ObjectID(BaseID):
    """Derived from parent task id + return/put index (reference: id.h ObjectID)."""

    SIZE = 20
    PREFIX = "obj"

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_stream(cls, task_id: TaskID, index: int):
        # Streamed (generator) yields: own index namespace so they never
        # clash with declared returns (reference: dynamic return ids).
        return cls(task_id.binary() + (index | 0x40000000).to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # High bit of the index distinguishes puts from returns.
        return cls(task_id.binary() + (put_index | 0x80000000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bin[: TaskID.SIZE])


# Backwards-friendly aliases matching the public reference naming.
ObjectRefID = ObjectID
