"""Head (control-plane hub) + driver runtime.

The Head is the analog of the reference's GCS server process *plus* the
driver-side CoreWorker ownership machinery collapsed into the driver process:

- task records with retries & dependency resolution before scheduling
  (reference: task_manager.cc + transport/dependency_resolver.cc),
- actor lifecycle FSM with restarts (reference: gcs_actor_manager.cc),
- object directory + node-to-node transfer on demand (reference:
  object_manager.cc pull/push),
- lineage-based object reconstruction: lost large objects are re-created by
  resubmitting the task that produced them (reference:
  object_recovery_manager.h:90, lineage_pinning_enabled),
- the public driver API surface: put/get/wait/submit (reference:
  python/ray/_private/worker.py).
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from . import fault_injection, ref_tracker, serialization
from .config import global_config
from .exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
    format_death_cause,
)
from .gcs import GCS, ActorInfo, JobInfo, NodeInfo, TaskEvent
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from .node import Node, WorkerHandle
from .object_ref import ObjectRef
from .scheduler import ClusterScheduler, PlacementGroup
from .task_spec import TaskSpec


@dataclass
class TaskRecord:
    spec: TaskSpec
    state: str = "PENDING"  # PENDING | WAITING_DEPS | QUEUED | RUNNING | FINISHED | FAILED
    node_hex: Optional[str] = None
    binding: Optional[dict] = None
    worker_id: Optional[WorkerID] = None
    missing_deps: Set[ObjectID] = field(default_factory=set)
    cancelled: bool = False
    unpinned: bool = False
    # settle/release guards: completion and crash handlers race (a failed
    # dispatch_to_worker send vs the node reader's worker-death report);
    # each attempt settles exactly once and releases resources exactly once
    settling: bool = False
    released: bool = False
    # actor creation only: scheduling-only resources were already returned
    # (death/restart must then release retained_resources, not the full set)
    shrunk: bool = False
    # monotonic stamp when the record reached FINISHED/FAILED (GC TTL)
    settled_at: Optional[float] = None


@dataclass
class ActorRecord:
    actor_id: ActorID
    creation_spec: TaskSpec
    state: str = "PENDING_CREATION"
    node_hex: Optional[str] = None
    worker_id: Optional[WorkerID] = None
    pending: deque = field(default_factory=deque)  # queued method specs
    inflight: Set[TaskID] = field(default_factory=set)
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: Optional[str] = None


class NodeProxy:
    """Head-side handle to a node daemon running in another OS process/host.

    Implements the slice of the Node interface the Head drives (dispatch,
    actor-worker dispatch, kill/cancel, store delete) by forwarding over the
    daemon's TCP channel; object payloads move separately via direct
    node-to-node pulls (object_transfer.py). Analog of the reference's
    per-raylet gRPC clients (node_manager.proto lease/cancel RPCs)."""

    def __init__(self, head, node_id: NodeID, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]], channel,
                 object_addr, pid: Optional[int]):
        from .resources import NodeResources

        self.head = head
        self.node_id = node_id
        self.hex = node_id.hex()
        unit = set(global_config().unit_instance_resources.split(","))
        self.resources = NodeResources(resources, unit_instance_names=unit)
        self.resources.labels = labels or {}
        self.resources_total = dict(resources)
        self.labels = labels or {}
        self.channel = channel
        self.object_addr = tuple(object_addr)
        self.pid = pid
        self.alive = True
        self.last_pong = time.monotonic()
        # clock-offset estimation against this daemon's wall clock
        # (flight-recorder trace merge); fed by stamped ping/pong pairs
        self._ping_sent: Optional[tuple] = None
        self.clock_est = None

    def _send(self, tag: str, *payload) -> bool:
        try:
            self.channel.send(tag, *payload)
            return True
        except (OSError, EOFError, ValueError):
            return False

    def dispatch(self, spec: TaskSpec, binding: dict) -> None:
        # a failed send is handled like node death: the channel reader's EOF
        # fires remove_node, which retries RUNNING tasks recorded on this node
        self._send("dispatch", pickle.dumps(spec), binding)

    def dispatch_to_worker(self, worker_id: WorkerID, spec: TaskSpec) -> bool:
        # optimistic: a dead worker is reported back by the daemon
        return self._send("dispatch_worker", worker_id, pickle.dumps(spec))

    def kill_worker(self, worker_id: WorkerID) -> None:
        self._send("kill_worker", worker_id)

    def cancel_task(self, task_id, worker_id, force: bool) -> None:
        self._send("cancel", task_id, worker_id, force)

    def store_delete(self, oid: ObjectID) -> None:
        self._send("store_delete", oid)

    def shutdown(self) -> None:
        self.alive = False
        self._send("shutdown")
        self.channel.close()


class Head:
    """Cluster brain living in the driver process."""

    def __init__(self, resources: Dict[str, float], session_dir: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 storage: Optional[str] = None):
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="raytpu_session_")
        os.makedirs(self.session_dir, exist_ok=True)
        self.job_id = JobID.from_random()
        store = None
        if storage:
            # durable GCS tables (reference: RedisStoreClient GCS FT)
            from .gcs_store import FileStore

            store = FileStore(os.path.join(storage, "gcs"))
        self.gcs = GCS(store=store)
        self.gcs.add_job(JobInfo(self.job_id))
        # cluster event log: this head is the process-local sink (GCS ring
        # + JSONL under session_dir/logs/events/); workers and daemons
        # reach record_cluster_events over their channels ("cevents")
        from ray_tpu.util import events as events_mod

        cfg0 = global_config()
        self._event_writer = None
        if cfg0.event_log_enabled:
            try:
                self._event_writer = events_mod.EventLogWriter(
                    self.session_dir)
            except OSError:
                self._event_writer = None
        events_mod.set_sink(self.record_cluster_events,
                            cfg0.cluster_event_flush_ms / 1000.0)
        # metrics history: sample the merged registry into bounded rings
        self.metrics_history = None
        if cfg0.metrics_history_enabled:
            from ray_tpu.util.metrics import MetricsHistory

            self.metrics_history = MetricsHistory(
                cfg0.metrics_history_max_samples)
        from .pubsub import PubsubBroker

        # general pubsub channels (reference: src/ray/pubsub/publisher.h)
        self.pubsub = PubsubBroker()
        self.scheduler = ClusterScheduler(self._dispatch_to_node)
        # placement specs journal through the GCS store (restart seed)
        self.scheduler.persist_pg = self.gcs.persist_placement
        self.nodes: Dict[str, Node] = {}
        from .lock_debug import tracked_rlock

        self._lock = tracked_rlock("Head._lock")
        self._object_cv = threading.Condition(self._lock)
        self.tasks: Dict[TaskID, TaskRecord] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self._waiting_on: Dict[ObjectID, Set[TaskID]] = defaultdict(set)
        self.ref_counts: Dict[ObjectID, int] = defaultdict(int)
        self.streams: Dict[TaskID, int] = {}  # HEAD-PATH task_id -> items
        # Owner hooks installed by DriverRuntime: the driver process's
        # direct manager IS an owner like any worker — its in-flight arg
        # pins guard deletes (extra_pin_check), its pin table joins the
        # memory view (owner_pin_counts), and its published streams serve
        # subscribers (owner_stream_next). These terminate at the OWNER
        # table, not head records: direct-path streams and pins never
        # create head state.
        self.extra_pin_check: Optional[Callable[[ObjectID], bool]] = None
        self.owner_pin_counts: Optional[Callable[[], dict]] = None
        self.owner_stream_next: Optional[Callable] = None
        # deletes deferred while an owner pin was live (released via
        # release_owner_pins on the task-settle reply chain) — durable:
        # a head bounce must not lose one (the delete would leak) or
        # forget the lease guard (the delete would double-apply early)
        self._deferred_deletes: Set[ObjectID] = {
            ObjectID(b) for b in self.gcs.meta.get("deferred_deletes", ())}
        self.node_loads: Dict[str, dict] = {}  # node hex -> syncer snapshot
        # daemon-held arg leases, piggybacked on the sync cadence
        # (kept apart from node_loads, which must stay JSON-safe).
        # Recovered lease views guard deferred deletes until the daemon
        # re-registers (fresh view) or the rejoin grace declares it dead.
        self._daemon_leases: Dict[str, set] = {
            h: {ObjectID(b) for b in oids}
            for h, oids in (self.gcs.meta.get("daemon_leases") or {}).items()}
        # head incarnation: bumped on every construction-from-storage and
        # every bounce; daemons echo it on the syncer so a restarted head
        # can tell stale registrations from current ones
        self.epoch = int(self.gcs.meta.get("epoch", 0)) + 1
        self.gcs.set_meta("epoch", self.epoch)
        # daemons expected to re-register after a bounce/restart, and the
        # deadline after which the ones that didn't are declared dead
        self._rejoin_pending: Set[str] = set()
        self._view_version = 0
        self._stopped = False
        self._node_listener = None
        self.node_server_address = None
        self._cluster_key: Optional[bytes] = None
        self._daemon_pool = None
        # routable IP local nodes advertise (loopback until a non-loopback
        # node server opens — see start_node_server)
        self.node_ip = os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1")
        # wait() waiters woken by any object seal (mixed direct+head wait)
        self._seal_events: Set[threading.Event] = set()
        # driver-owner lineage recovery for direct-path results (wired by
        # DriverRuntime; consulted when a lost object has no head record)
        self.direct_recover: Optional[Callable[[ObjectID], bool]] = None
        # fetch_local pulls in flight (dedup across concurrent waits)
        self._active_pulls: Set[ObjectID] = set()
        # flight-recorder: reported span batches per source id
        # ("<node6>:<pid>" / "<node6>:daemon" / "head:<label>"), merged
        # into one Perfetto trace by flight_recorder.cluster_trace
        from ray_tpu.util import flight_recorder as _fr

        _fr.adopt_config(cfg0)
        _fr.set_process_label("driver")
        _fr.set_dump_dir(self.session_dir)
        self.flight_spans: Dict[str, deque] = {}
        # memory observability: per-source worker ref-table reports
        # (source id = "<node6>:<pid>", same keying as worker metrics)
        # and pending head->daemon store_info requests
        self._ref_reports: Dict[str, dict] = {}
        self._store_info_seq = 0
        self._store_info_pending: Dict[int, list] = {}
        # pending head->daemon cluster stack-dump requests (same
        # request/reply shape as store_info: slot = [event, reply, hex])
        self._stack_seq = 0
        self._stack_pending: Dict[int, list] = {}
        # (monotonic_ts, rows) — memory_table joins are cached briefly so
        # a dashboard polling /api/objects doesn't pay a store_info
        # round-trip to every daemon per request
        self._memory_table_cache: Tuple[float, Optional[List[dict]]] = \
            (0.0, None)
        # head node (the driver's node)
        self.head_node = self.add_node(resources, labels=labels)
        # service threads are retained so shutdown() can join them; the
        # loops pace on _stop_event so the joins return immediately
        self._stop_event = threading.Event()
        self._service_threads: List[threading.Thread] = []
        if global_config().task_record_ttl_s > 0:
            self._spawn_service(self._record_gc_loop, "task-record-gc")
        if self.metrics_history is not None:
            self._spawn_service(self._metrics_history_loop,
                                "metrics-history")
        # goodput observatory (train/health.py): badput ledger +
        # straggler/regression/TTRT detectors on their own cadence
        self.health_monitor = None
        if cfg0.health_monitor_enabled:
            self._spawn_service(self._health_monitor_loop,
                                "health-monitor")
        # restart recovery: re-create durable placements + detached
        # actors, retire owner-bound ones (must run after head_node is up)
        self._recover_durable_state()

    def _spawn_service(self, target, name: str) -> threading.Thread:
        """Start a head service loop and retain the handle for the
        shutdown join (resource-lifecycle: a class with a teardown
        method owns every thread it starts)."""
        t = threading.Thread(target=target, daemon=True, name=name)
        self._service_threads.append(t)
        t.start()
        return t

    # ------------------------------------------------- restart recovery

    def _recover_durable_state(self) -> None:
        """Rehydrate the durable GCS-analog tables into live runtime
        state (reference: GCS server restart with RedisStoreClient —
        gcs_actor_manager/gcs_placement_group_manager table replay).

        Placements re-reserve under their original ids; DETACHED actors
        with a journaled creation spec re-create from it (their owner is
        the cluster, so they survive the head); owner-bound actors are
        retired DEAD — their owner (the old driver process) died with
        the head. Recovered object-directory entries stay inert until a
        node with that hex re-registers (every lookup filters on live
        membership); stale ones are dropped after the rejoin grace."""
        recovered_pgs = self.gcs.recovered_placements
        for pg_hex, rec in list(recovered_pgs.items()):
            try:
                self.scheduler.create_placement_group(
                    rec["bundles"], rec.get("strategy", "PACK"),
                    rec.get("name", ""),
                    pg_id=PlacementGroupID(bytes.fromhex(pg_hex)))
            except Exception:
                pass  # an unreadable spec must not block recovery
        stale_hexes: Set[str] = set()
        for info in self.gcs.list_actors():
            with self._lock:
                known = info.actor_id in self.actors
            if known or info.state == "DEAD":
                continue
            if info.node_hex:
                stale_hexes.add(info.node_hex)
            if info.detached and info.creation_spec:
                try:
                    spec = pickle.loads(info.creation_spec)
                except Exception:
                    spec = None
                if spec is not None:
                    self._recreate_recovered_actor(info, spec)
                    continue
            self.gcs.update_actor(
                info.actor_id, state="DEAD",
                death_cause="head restarted; non-detached actor died "
                            "with its owner")
            self.gcs.remove_actor_name(info.actor_id)
        # nodes the durable tables still reference: give them the rejoin
        # grace to re-register before their directory entries are purged
        with self.gcs._lock:
            for locs in self.gcs.object_dir.values():
                stale_hexes.update(locs)
        with self._lock:
            stale_hexes -= set(self.nodes)
        if stale_hexes:
            self._rejoin_pending.update(stale_hexes)
            self._spawn_rejoin_reaper()

    def _recreate_recovered_actor(self, info, spec: TaskSpec) -> None:
        """Resubmit a recovered detached actor's creation under a fresh
        task id (its old incarnation died with the old head)."""
        import copy

        new_spec = copy.deepcopy(spec)
        new_spec.task_id = TaskID.from_random()
        new_spec.attempt = 0
        arec = ActorRecord(info.actor_id, creation_spec=new_spec,
                          max_restarts=info.max_restarts,
                          num_restarts=info.num_restarts)
        arec.state = "RESTARTING"
        with self._lock:
            self.actors[info.actor_id] = arec
            self.tasks[new_spec.task_id] = TaskRecord(new_spec)
        self.gcs.update_actor(info.actor_id, state="RESTARTING",
                              node_hex=None)
        self._resolve_then_queue(self.tasks[new_spec.task_id])

    def _expect_rejoin(self, proxy: "NodeProxy") -> None:
        """Detach a daemon we told (or expect) to re-register WITHOUT
        running the death path: its actors and objects stay intact for
        the replay; only if it misses the grace window does the reaper
        declare it lost. Marking .alive False first keeps the reader's
        EOF handler from invoking remove_node and killing healthy
        max_restarts=0 actors whose workers are still running."""
        proxy.alive = False
        with self._lock:
            self.nodes.pop(proxy.hex, None)
            self._rejoin_pending.add(proxy.hex)
        self._fail_store_info_waiters(proxy.hex)
        self._fail_stack_waiters(proxy.hex)
        try:
            proxy.channel.close()
        except Exception:
            pass
        self.scheduler.remove_node(proxy.hex)
        self._spawn_rejoin_reaper()

    def _spawn_rejoin_reaper(self) -> None:
        """After the rejoin grace, nodes that never (re-)registered are
        declared dead: their directory entries purge, their actors fail
        over per max_restarts, their lease views stop guarding deletes.
        At most one reaper runs at a time (repeated reregister kicks
        must not pile up service threads)."""
        with self._lock:
            if getattr(self, "_rejoin_reaper_active", False):
                return
            self._rejoin_reaper_active = True
        grace = max(0.1, global_config().daemon_rejoin_grace_s)

        def run():
            try:
                if self._stop_event.wait(grace) or self._stopped:
                    return
                with self._lock:
                    gone = {h for h in self._rejoin_pending
                            if h not in self.nodes}
                    self._rejoin_pending.clear()
                # cold path, bounded by cluster size: runs once per
                # bounce/restart, for daemons that never came back
                for node_hex in gone:
                    # graftlint: ignore[thread-hygiene]
                    self._declare_node_lost(node_hex)
            finally:
                with self._lock:
                    self._rejoin_reaper_active = False

        self._spawn_service(run, "rejoin-grace")

    def _declare_node_lost(self, node_hex: str) -> None:
        """Death handling for a node we have no live connection to (it
        never re-registered after a bounce/restart): everything
        remove_node does, minus the proxy shutdown."""
        self.gcs.mark_node_dead(node_hex)
        from ray_tpu.util import events as events_mod

        events_mod.emit("WARNING", events_mod.SOURCE_NODE,
                        f"node {node_hex[:8]} did not re-register after "
                        "head restart; declared dead", entity_id=node_hex)
        if self._node_listener is not None:
            self._broadcast_cluster_view()
        self._fail_node_workloads(
            node_hex, "node did not re-register after head restart")

    def _fail_node_workloads(self, node_hex: str, cause: str) -> None:
        """The one post-disconnect failover body remove_node and
        _declare_node_lost share: fail parked store-info collectors,
        release the daemon's lease view (retrying deletes parked behind
        it), purge the node's directory entries, fail/retry its RUNNING
        head-path tasks, and fail over its actors per max_restarts."""
        self._fail_store_info_waiters(node_hex)
        self._fail_stack_waiters(node_hex)
        retry_deletes = []
        with self._lock:
            self.node_loads.pop(node_hex, None)
            if self._daemon_leases.pop(node_hex, None):
                self._persist_leases_locked()
                retry_deletes = [oid for oid in self._deferred_deletes
                                 if self.ref_counts.get(oid, 0) <= 0]
        for oid in retry_deletes:
            if not self._stopped:
                self.delete_object(oid)
        self.gcs.drop_node_objects(node_hex)
        # RUNNING head-path tasks on the node have no one left to ever
        # report them: fail/retry now or their callers park until timeout
        with self._lock:
            affected = [r for r in self.tasks.values()
                        if r.state == "RUNNING" and r.node_hex == node_hex]
            dead_actors = [a for a in self.actors.values()
                           if a.node_hex == node_hex
                           and a.state in ("ALIVE", "PENDING_CREATION")]
        # cold path (once per dead node); retry/backoff threads are one
        # per affected task/actor
        for rec in affected:
            # graftlint: ignore[thread-hygiene]
            self._handle_task_failure(
                rec, WorkerCrashedError(cause), results=None)
        for arec in dead_actors:
            # graftlint: ignore[thread-hygiene]
            self._handle_actor_failure(
                arec, format_death_cause(cause, node_hex))
        with self._object_cv:
            self._object_cv.notify_all()

    def _persist_deferred_locked(self) -> None:
        if self.gcs._durable:
            self.gcs.set_meta("deferred_deletes",
                              [o.binary() for o in self._deferred_deletes])

    def _persist_leases_locked(self) -> None:
        if self.gcs._durable:
            self.gcs.set_meta(
                "daemon_leases",
                {h: [o.binary() for o in oids]
                 for h, oids in self._daemon_leases.items()})

    # ------------------------------------------------------- observability

    def record_cluster_events(self, events: List[dict]) -> None:
        """Event-log sink: absorb a batch of structured cluster events
        (local emitters, worker channels, daemon links all funnel here)."""
        for ev in events:
            self.gcs.record_cluster_event(ev)
        if self._event_writer is not None:
            self._event_writer.write(events)

    def on_ref_report(self, source_id: str, table: dict) -> None:
        """Absorb one process's ref-table export (full state per source,
        so re-reports overwrite — mirror of on_worker_metrics)."""
        with self._lock:
            self._ref_reports[source_id] = table

    def collect_store_infos(self, timeout: float = 1.0) -> Dict[str, list]:
        """Per-node store dumps: local nodes by direct call, daemons via
        a bounded ``store_info`` round-trip over the control channel.
        Returns {node_hex: [(oid, size, inline, spilled, created_ts,
        store_ref_count)]}; unreachable/slow daemons are simply absent."""
        out: Dict[str, list] = {}
        waiters = []
        with self._lock:
            nodes = list(self.nodes.items())
        for h, n in nodes:
            if self._is_local(n):
                out[h] = n.store.object_infos()
            elif getattr(n, "alive", False):
                with self._lock:
                    self._store_info_seq += 1
                    req_id = self._store_info_seq
                    slot = [threading.Event(), None, h]
                    self._store_info_pending[req_id] = slot
                if n._send("store_info", req_id):
                    waiters.append((h, req_id, slot))
                else:
                    self._store_info_pending.pop(req_id, None)
        deadline = time.monotonic() + timeout
        for h, req_id, slot in waiters:
            slot[0].wait(max(0.0, deadline - time.monotonic()))
            self._store_info_pending.pop(req_id, None)
            if slot[1] is not None:
                out[h] = slot[1]
        return out

    def _fail_store_info_waiters(self, node_hex: str) -> None:
        """A daemon died: collectors parked on its ``store_info`` round
        learn now instead of waiting out the rest of their timeout."""
        with self._lock:
            gone = [(rid, s) for rid, s in self._store_info_pending.items()
                    if len(s) > 2 and s[2] == node_hex]
            for rid, _s in gone:
                self._store_info_pending.pop(rid, None)
        for _rid, slot in gone:
            slot[0].set()  # slot[1] stays None: the node is simply absent

    def collect_stacks(self, timeout: float = 5.0,
                       duration_ms: Optional[int] = None) -> Dict[str, str]:
        """Cluster-wide collapsed-stack dump (`python -m ray_tpu stack`):
        one bounded sampling round per process — this head directly,
        local nodes' workers over their channels, remote daemons (and
        their workers) via a ``stack_dump`` round-trip. Returns
        {source: collapsed-stack text}; unreachable processes are
        simply absent."""
        from ray_tpu.util import sampling_profiler

        dur_ms = global_config().stack_dump_duration_ms \
            if duration_ms is None else duration_ms
        dur = max(0.0, dur_ms / 1000.0)
        out: Dict[str, str] = {}
        waiters = []
        with self._lock:
            nodes = list(self.nodes.items())
        for h, n in nodes:
            if self._is_local(n):
                continue  # local workers gathered below, off the clock
            if getattr(n, "alive", False):
                with self._lock:
                    self._stack_seq += 1
                    req_id = self._stack_seq
                    slot = [threading.Event(), None, h]
                    self._stack_pending[req_id] = slot
                if n._send("stack_dump", req_id, dur_ms):
                    waiters.append((req_id, slot))
                else:
                    self._stack_pending.pop(req_id, None)
        # sample this process while the daemons sample theirs
        out[f"head:{os.getpid()}"] = sampling_profiler.collect_stacks(dur)
        for h, n in nodes:
            if self._is_local(n):
                out.update(n.collect_worker_stacks(dur, timeout=timeout))
        deadline = time.monotonic() + timeout
        for req_id, slot in waiters:
            slot[0].wait(max(0.0, deadline - time.monotonic()))
            self._stack_pending.pop(req_id, None)
            if slot[1] is not None:
                out.update(slot[1])
        return out

    def _fail_stack_waiters(self, node_hex: str) -> None:
        """Same death path as store_info: wake stack collectors parked
        on a daemon that just died."""
        with self._lock:
            gone = [(rid, s) for rid, s in self._stack_pending.items()
                    if len(s) > 2 and s[2] == node_hex]
            for rid, _s in gone:
                self._stack_pending.pop(rid, None)
        for _rid, slot in gone:
            slot[0].set()

    def _health_monitor_loop(self) -> None:
        from ray_tpu.train.health import HealthMonitor

        self.health_monitor = HealthMonitor(self)
        period = max(0.05,
                     global_config().health_monitor_interval_ms / 1000.0)
        while not self._stop_event.wait(period):
            try:
                self.health_monitor.tick()
            except Exception:
                pass  # observability must never take the head down

    def memory_table(self, limit: int = 100_000,
                     timeout: float = 1.0) -> List[dict]:
        """The cluster ownership table (the ``ray memory`` backend): joins
        the object directory + per-node store dumps (bytes, spill state)
        with the owner-side ref tables (creator callsite/kind, local-ref
        and borrow counts) — driver's table read in-process, workers' from
        their periodic ``refs`` reports. Joins are cached for 1 s (rows
        are copied out, so callers may mutate them)."""
        cache_ts, cached = self._memory_table_cache
        if cached is not None and time.monotonic() - cache_ts < 1.0:
            return [dict(r) for r in cached[:limit]]
        store_infos = self.collect_store_infos(timeout)
        tables = [ref_tracker.export()]  # this (driver) process
        with self._lock:
            tables.extend(self._ref_reports.values())
            pins = {oid: n for oid, n in self.ref_counts.items() if n > 0}
        if self.owner_pin_counts is not None:
            # the driver's owner-side in-flight arg pins (these replaced
            # head pin_delta on the direct path) join the pinned column
            for oid, n in self.owner_pin_counts().items():
                pins[oid] = pins.get(oid, 0) + n
        now = time.time()
        rows: Dict[ObjectID, dict] = {}

        def row(oid: ObjectID) -> dict:
            r = rows.get(oid)
            if r is None:
                r = rows[oid] = {
                    "object_id": oid.hex(), "size": None, "locations": [],
                    "inline": False, "spilled": False,
                    "pinned": pins.get(oid, 0),
                    "local_refs": 0, "borrows": 0,
                    # set from the owner-side kind below (the id's index
                    # bits are random garbage for from_random puts, so
                    # they can't be trusted as a stream marker)
                    "stream": False,
                    "kind": None, "callsite": None, "creator": None,
                    "age_s": None,
                }
            return r

        for node_hex, infos in store_infos.items():
            for oid, size, inline, spilled, created_ts, _rc in infos:
                r = row(oid)
                r["locations"].append(node_hex)
                r["size"] = max(r["size"] or 0, size)
                r["inline"] = r["inline"] or inline
                r["spilled"] = r["spilled"] or spilled
                if r["age_s"] is None:
                    r["age_s"] = round(max(0.0, now - created_ts), 3)
        for table in tables:
            for oid, entry in table.items():
                count, kind, size, callsite, creator, created_at = entry
                r = row(oid)
                if kind == ref_tracker.KIND_BORROW:
                    r["borrows"] += count
                else:
                    r["local_refs"] += count
                    if r["kind"] is None:
                        r["kind"] = kind
                    if kind == ref_tracker.KIND_STREAM_ITEM:
                        r["stream"] = True
                    if r["callsite"] is None and callsite:
                        r["callsite"] = callsite
                    if r["creator"] is None and creator:
                        r["creator"] = creator
                if r["size"] is None and size:
                    r["size"] = int(size)
                if r["age_s"] is None and created_at:
                    r["age_s"] = round(max(0.0, now - created_at), 3)
        # directory-known objects the store dumps missed (e.g. a daemon
        # that timed out, or an object whose handles were all dropped):
        # every directory entry gets a row, so the table never under-
        # reports just because a node was slow to answer store_info
        with self._lock:
            node_set = set(self.nodes)
        with self.gcs._lock:
            dir_snap = {oid: set(locs)
                        for oid, locs in self.gcs.object_dir.items()}
        for oid, locs in dir_snap.items():
            r = row(oid)
            for h in locs:
                if h in node_set and h not in r["locations"]:
                    r["locations"].append(h)
        out = list(rows.values())
        self._memory_table_cache = (time.monotonic(), out)
        return [dict(r) for r in out[:limit]]

    def sample_metrics_history(self) -> None:
        """Take one sample of every metric series now (the loop calls this
        on the configured interval; tests call it directly)."""
        if self.metrics_history is not None:
            from ray_tpu.util.metrics import registry

            self.metrics_history.sample(registry())

    def _metrics_history_loop(self) -> None:
        period = max(0.05,
                     global_config().metrics_history_interval_ms / 1000.0)
        while not self._stop_event.wait(period):
            try:
                self.sample_metrics_history()
            except Exception:
                pass  # sampling must never kill the loop

    # ------------------------------------------------------- record GC

    def _record_gc_loop(self) -> None:
        """Fold settled task records into the (already-capped) event ring
        after a TTL, bounding head memory for long-running drivers
        (reference: GcsTaskManager's capped task storage). Records stay
        while (a) their results are still referenced — lineage
        reconstruction needs the spec — or (b) they created a still-alive
        actor incarnation (its death must release the reservation)."""
        cfg = global_config()
        period = max(1.0, cfg.task_record_gc_period_s)
        while not self._stop_event.wait(period):
            try:
                self.gc_task_records(cfg.task_record_ttl_s)
                # idle pubsub rings fold to tombstones on the same cadence
                self.pubsub.gc(idle_ttl_s=max(600.0,
                                              cfg.task_record_ttl_s * 5))
            except Exception:
                pass  # never let bookkeeping kill the sweeper

    def gc_task_records(self, ttl_s: float) -> int:
        now = time.monotonic()
        dropped = 0
        stream_pins: List[ObjectID] = []
        with self._lock:
            for tid, rec in list(self.tasks.items()):
                if rec.state not in ("FINISHED", "FAILED"):
                    continue
                if rec.settled_at is None or now - rec.settled_at < ttl_s:
                    continue
                spec = rec.spec
                if spec.is_actor_creation:
                    arec = self.actors.get(spec.actor_id)
                    if (arec is not None and arec.state != "DEAD"
                            and arec.creation_spec is spec):
                        continue  # live incarnation: needed at death
                if any(self.ref_counts.get(oid, 0) > 0
                       for oid in spec.return_ids()):
                    continue  # lineage: results still referenced
                count = self.streams.pop(tid, None)
                if count:
                    stream_pins.extend(
                        ObjectID.for_stream(tid, i) for i in range(count))
                del self.tasks[tid]
                dropped += 1
            # dead-actor records past the TTL fold away too
            for aid, arec in list(self.actors.items()):
                if arec.state != "DEAD":
                    continue
                crec = self.tasks.get(arec.creation_spec.task_id) \
                    if arec.creation_spec is not None else None
                if crec is None or (crec.settled_at is not None
                                    and now - crec.settled_at >= ttl_s):
                    del self.actors[aid]
        if stream_pins:
            self.apply_pin_delta(stream_pins, -1)
        return dropped

    # ------------------------------------------------------------ membership

    def add_node(self, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 node_ip: Optional[str] = None) -> Node:
        node = Node(self, NodeID.from_random(), resources, self.session_dir,
                    labels, node_ip=node_ip or self.node_ip)
        if self._cluster_key is not None:
            node.start_object_server(self._cluster_key)
        with self._lock:
            self.nodes[node.hex] = node
        self.gcs.register_node(NodeInfo(node.node_id, node.hex,
                                        resources_total=dict(resources),
                                        labels=labels or {}))
        self.scheduler.add_node(node.hex, node.resources)
        from ray_tpu.util import events as events_mod

        events_mod.emit("INFO", events_mod.SOURCE_NODE,
                        f"node {node.hex[:8]} alive (in-process)",
                        entity_id=node.hex, resources=dict(resources))
        if self._node_listener is not None:
            self._broadcast_cluster_view()
        return node

    # --------------------------------------------------------- multi-host
    @staticmethod
    def _is_local(node) -> bool:
        return hasattr(node, "store")

    def start_node_server(self, host: str = "127.0.0.1", port: int = 0):
        """Open the TCP join endpoint for remote node daemons and start
        object servers on local nodes so daemons can pull from them.

        Analog of the GCS server socket + per-node ObjectManager listeners
        (gcs_server_main.cc / object_manager.proto:61). Returns (host, port).
        """
        from concurrent.futures import ThreadPoolExecutor

        from .protocol import make_listener

        if self._node_listener is not None:
            return self.node_server_address
        self._cluster_key = os.urandom(16)
        self._node_listener = make_listener((host, port), self._cluster_key)
        self.node_server_address = self._node_listener.address
        self._daemon_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="daemon-req")
        # serving off-box daemons: local nodes must advertise a routable IP,
        # not loopback, or cross-host pulls/Train bootstrap dial themselves
        if self.node_ip.startswith("127.") and not host.startswith("127."):
            from .protocol import infer_node_ip

            self.node_ip = (host if host not in ("0.0.0.0", "::")
                            else infer_node_ip())
        with self._lock:
            nodes = [n for n in self.nodes.values() if self._is_local(n)]
        for n in nodes:
            if n.node_ip.startswith("127."):
                n.update_node_ip(self.node_ip)
            n.start_object_server(self._cluster_key)
        self._spawn_service(self._node_accept_loop, "node-server")
        self._spawn_service(self._health_check_loop, "health-prober")
        return self.node_server_address

    def on_node_sync(self, proxy, snap: dict) -> None:
        """Merge a daemon's load report (reference: RaySyncer RESOURCE_VIEW
        consumption in the GCS). A sync also counts as liveness."""
        # head-incarnation check: a daemon still syncing under a pre-
        # bounce epoch somehow kept a live channel into the restarted
        # head — tell it to drop the link and re-register (EOF detection
        # is the normal path; this is the belt to its braces)
        ep = snap.pop("epoch", None)
        if ep is not None and ep != self.epoch:
            proxy._send("reregister")
            self._expect_rejoin(proxy)
            return
        # leases travel on the sync but live in their own table —
        # node_loads stays JSON-safe for the state API / dashboard
        leases = set(snap.pop("leases", None) or ())
        retry_deletes = []
        with self._lock:
            cur = self.node_loads.get(proxy.hex)
            if cur is not None and cur.get("version", 0) >= snap.get(
                    "version", 0):
                return  # stale out-of-order update
            self.node_loads[proxy.hex] = snap
            if self._daemon_leases.get(proxy.hex) != leases:
                self._daemon_leases[proxy.hex] = leases
                self._persist_leases_locked()
            if self._deferred_deletes:
                # a daemon lease releasing shows up as the oid vanishing
                # from its sync view: retry deletes parked behind it
                retry_deletes = [oid for oid in self._deferred_deletes
                                 if oid not in leases
                                 and self.ref_counts.get(oid, 0) <= 0]
        proxy.last_pong = time.monotonic()
        for oid in retry_deletes:
            if not self._stopped:
                self.delete_object(oid)  # rechecks every pin/lease guard
        info = self.gcs.nodes.get(proxy.hex)
        if info is not None:
            info.last_heartbeat = time.monotonic()
        # keep daemons' peer-load views fresh for direct-task spillback
        # (rate-limited; reference: RaySyncer periodic re-broadcast)
        now = time.monotonic()
        if now - getattr(self, "_last_view_broadcast", 0.0) > 0.5:
            self._last_view_broadcast = now
            self._broadcast_cluster_view()

    def publish_oneway(self, channel: str, message) -> None:
        """One-way pubsub publish from a node/worker (no reply)."""
        self.pubsub.publish(channel, message)

    def apply_pin_delta(self, oids, delta: int) -> None:
        """Batched ref-count adjustment (direct-path arg pinning)."""
        to_delete = []
        with self._lock:
            for oid in oids:
                self.ref_counts[oid] += delta
                if delta < 0 and self.ref_counts[oid] <= 0:
                    to_delete.append(oid)
        if not self._stopped:
            for oid in to_delete:
                self.delete_object(oid)

    def on_sealed_payload(self, oid: ObjectID, payload: bytes,
                          is_error: bool) -> None:
        """Rare-path escape hatch: an executor node couldn't store a direct
        result (arena full) — seal the bytes in the head store so head-path
        consumers can still resolve the ref."""
        self.head_node.store.put_inline(oid, payload, is_error)
        self.on_object_sealed(oid, self.head_node.hex)

    def publish_direct_events(self, node_hex: str, events) -> None:
        """Apply a node's batched direct-task event report: object
        locations (for cross-node consumers) + observability events. The
        head does no per-task bookkeeping on this path — this batch is its
        ONLY involvement (reference: GcsTaskManager as a pure event sink,
        gcs_task_manager.h:86)."""
        from ray_tpu.util.metrics import registry

        for task_id_b, fn_name, err_name, sealed_oids, t0, t1 in events:
            for oid in sealed_oids:
                # full seal handling: location + WAITING_DEPS wakeups
                self.on_object_sealed(oid, node_hex)
            if global_config().task_events_enabled:
                # RUNNING + terminal pair so timeline/state get durations
                self.gcs.record_task_event(TaskEvent(
                    task_id=task_id_b, name=fn_name, state="RUNNING",
                    node_hex=node_hex, ts=t0, attempt=0, error=None))
                self.gcs.record_task_event(TaskEvent(
                    task_id=task_id_b, name=fn_name,
                    state="FAILED" if err_name else "FINISHED",
                    node_hex=node_hex, ts=t1, attempt=0,
                    error=err_name))
        registry().record("ray_tpu_tasks_total", "counter",
                          "task state transitions", (("state", "DIRECT"),),
                          float(len(events)), mode="add")
        with self._object_cv:
            self._object_cv.notify_all()

    def _broadcast_cluster_view(self) -> None:
        """Fan the merged membership view out to every daemon (reference:
        RaySyncer broadcast of the aggregated resource view)."""
        with self._lock:
            self._view_version += 1
            version = self._view_version
            proxies = [n for n in self.nodes.values()
                       if isinstance(n, NodeProxy) and n.alive]
        with self.gcs._lock:  # snapshot: registrations mutate concurrently
            infos = list(self.gcs.nodes.values())
        view = []
        for info in infos:
            node = self.nodes.get(info.hex)
            addr = None
            queue = 0
            if node is not None:
                if self._is_local(node):
                    srv = getattr(node, "object_server", None)
                    addr = list(srv.address) if srv else None
                    queue = len(node._local_queue)
                else:
                    addr = list(node.object_addr)
                    queue = self.node_loads.get(info.hex, {}).get(
                        "queue_depth", 0)
            view.append({"hex": info.hex, "alive": info.alive,
                         "resources": info.resources_total,
                         "addr": addr, "queue": queue})
        for p in proxies:
            p._send("cluster_view", version, view)

    def _health_check_loop(self) -> None:
        """Active node probing (reference: gcs_health_check_manager.h:39 —
        periodic gRPC health checks with a miss threshold). EOF detection
        catches cleanly-dying daemons; this catches wedged ones."""
        cfg = global_config()
        period = max(0.1, cfg.health_check_period_ms / 1000.0)
        threshold = max(1, cfg.health_check_failure_threshold)
        seq = 0
        while not self._stop_event.wait(period):
            seq += 1
            with self._lock:
                proxies = [n for n in self.nodes.values()
                           if isinstance(n, NodeProxy) and n.alive]
            now = time.monotonic()
            for p in proxies:
                if now - p.last_pong > period * threshold:
                    p.alive = False
                    try:
                        p.channel.close()  # reader EOF completes cleanup
                    except Exception:
                        pass
                    self.remove_node(p.hex)
                    continue
                # stamp the send for clock-offset estimation: the pong
                # echoes seq plus the daemon's wall clock, and the
                # min-RTT midpoint estimator needs both endpoints' walls
                p._ping_sent = (seq, time.time())
                p._send("ping", seq)

    @property
    def cluster_key_hex(self) -> Optional[str]:
        return self._cluster_key.hex() if self._cluster_key else None

    def _node_accept_loop(self, listener=None) -> None:
        import multiprocessing.context as _mpctx

        from .protocol import Channel

        listener = listener or self._node_listener
        while not self._stopped:
            try:
                conn = listener.accept()
            except (OSError, EOFError, _mpctx.AuthenticationError):
                # a client dropping mid-handshake raises here too; only a
                # closed/superseded listener (shutdown or a head bounce
                # reopening the endpoint) ends the loop
                if self._stopped or self._node_listener is not listener:
                    return
                continue
            from .protocol import set_nodelay

            set_nodelay(conn)
            threading.Thread(target=self._register_daemon,
                             args=(Channel(conn),), daemon=True).start()

    def _register_daemon(self, channel) -> None:
        if self._stopped:
            channel.close()
            return
        try:
            tag, payload = channel.recv()
            assert tag == "hello"
            hello = payload[0] if payload else {}
            # rejoin (daemon re-registering after a head bounce): honor
            # its existing node hex so every route, lease, and actor
            # record that names this node stays valid
            rejoin_hex = (hello.get("rejoin")
                          if isinstance(hello, dict) else None)
            node_id = (NodeID(bytes.fromhex(rejoin_hex)) if rejoin_hex
                       else NodeID.from_random())
            from .protocol import PROTOCOL_VERSION

            channel.send("welcome", {
                "node_hex": node_id.hex(),
                "job_id": self.job_id.binary(),
                "config": global_config().to_json(),
                "proto": PROTOCOL_VERSION,
                "epoch": self.epoch,
            })
            tag, (ready,) = channel.recv()
            assert tag == "node_ready"
        except Exception:
            channel.close()
            return
        proxy = NodeProxy(self, node_id, ready["resources"],
                          ready.get("labels"), channel,
                          ready["object_addr"], ready.get("pid"))
        agent_addr = ready.get("agent_addr")
        proxy.agent_addr = tuple(agent_addr) if agent_addr else None
        if self._stopped:
            proxy.shutdown()
            return
        with self._lock:
            stale = self.nodes.get(proxy.hex)
            if stale is not None and stale is not proxy:
                # kill the old registration FIRST: its reader thread's
                # EOF handler checks .alive, and with it still True the
                # EOF would run remove_node(hex) — destroying the NEW
                # proxy we are about to install
                stale.alive = False
            self.nodes[proxy.hex] = proxy
        if stale is not None:
            try:
                stale.channel.close()
            except Exception:
                pass
        if stale is not None or rejoin_hex:
            # replace any half-dead registration wholesale so the
            # scheduler never double-counts the node's resources
            self.scheduler.remove_node(proxy.hex)
        self.gcs.register_node(NodeInfo(node_id, proxy.hex,
                                        resources_total=dict(ready["resources"]),
                                        labels=proxy.labels))
        self.scheduler.add_node(proxy.hex, proxy.resources)
        if rejoin_hex:
            self._apply_daemon_replay(proxy, ready.get("replay") or {})
        from ray_tpu.util import events as events_mod

        events_mod.emit("INFO", events_mod.SOURCE_NODE,
                        f"node {proxy.hex[:8]} alive (daemon pid="
                        f"{proxy.pid}"
                        f"{', rejoined' if rejoin_hex else ''})",
                        entity_id=proxy.hex,
                        resources=dict(ready["resources"]))
        self._broadcast_cluster_view()
        threading.Thread(target=self._daemon_reader, args=(proxy,),
                         daemon=True, name=f"daemon-{proxy.hex[:6]}").start()

    def _apply_daemon_replay(self, proxy: "NodeProxy", replay: dict) -> None:
        """Fold a rejoining daemon's replay snapshot into head state:
        object locations re-enter the directory, holder leases re-guard
        deferred deletes, and hosted actors revive as ALIVE with their
        routing (worker id) intact — the PR-7 owner-side tables converge
        back to the pre-crash view without the daemon having moved any
        state."""
        for oid in replay.get("objects", ()):
            self.gcs.add_object_location(oid, proxy.hex)
        with self._lock:
            self._daemon_leases[proxy.hex] = set(replay.get("leases", ()))
            self._persist_leases_locked()
            self._rejoin_pending.discard(proxy.hex)
        for aid, wid in replay.get("actors", ()):
            flush = []
            with self._lock:
                arec = self.actors.get(aid)
                if arec is None or arec.state == "DEAD":
                    continue
                if arec.node_hex not in (None, proxy.hex):
                    continue  # restarted elsewhere meanwhile: replay stale
                arec.state = "ALIVE"
                arec.node_hex = proxy.hex
                arec.worker_id = wid
                while arec.pending:
                    flush.append(arec.pending.popleft())
            self.gcs.update_actor(aid, state="ALIVE", node_hex=proxy.hex)
            for mspec in flush:
                rec = self.tasks.get(mspec.task_id)
                if rec is not None:
                    self._submit_actor_task(rec)
        with self._object_cv:
            self._object_cv.notify_all()  # gets parked on lost locations

    def _daemon_reader(self, proxy: "NodeProxy") -> None:
        import types

        while True:
            try:
                tag, payload = proxy.channel.recv()
            except (EOFError, OSError, TypeError):
                # TypeError: prober closed the connection mid-recv (the
                # CPython Connection zeroes its handle)
                if not self._stopped and proxy.alive:
                    proxy.alive = False
                    self.remove_node(proxy.hex)
                return
            if tag == "task_finished":
                (task_id, err_name, spec_b, binding, results, worker_id,
                 attempt) = payload
                spec = pickle.loads(spec_b) if spec_b else None
                self.on_task_finished(proxy, task_id, err_name, spec, binding,
                                      results, worker_id=worker_id,
                                      attempt=attempt)
            elif tag == "sealed":
                self.on_object_sealed(payload[0], proxy.hex)
            elif tag == "stream_item":
                self.on_stream_item(payload[0], payload[1])
            elif tag == "worker_metrics":
                self.on_worker_metrics(payload[0], payload[1])
            elif tag == "worker_log":
                self.on_worker_log(payload[0], payload[1], payload[2])
            elif tag == "worker_exit":
                w = types.SimpleNamespace(worker_id=payload[0],
                                          actor_id=payload[1], pid=payload[2])
                self.on_worker_exit(proxy, w)
            elif tag == "worker_crashed":
                wid, actor_id, pid, spec_b, binding, prev_state = payload
                w = types.SimpleNamespace(worker_id=wid, actor_id=actor_id,
                                          pid=pid)
                spec = pickle.loads(spec_b) if spec_b else None
                self.on_worker_crashed(proxy, w, spec, binding, prev_state)
            elif tag == "dispatch_worker_failed":
                task_id, actor_id = payload
                rec = self.tasks.get(task_id)
                if rec is not None:
                    self._handle_task_failure(
                        rec, ActorDiedError(actor_id, format_death_cause(
                            "actor node/worker gone", proxy.hex)),
                        None)
            elif tag == "pong":
                proxy.last_pong = time.monotonic()
                # new daemons echo (seq, their wall clock): feed the
                # min-RTT clock-offset estimator for trace merging.
                # Old 1-tuple pongs (or an unstamped ping) just skip it.
                if len(payload) >= 2:
                    sent = getattr(proxy, "_ping_sent", None)
                    if sent is not None and sent[0] == payload[0]:
                        if proxy.clock_est is None:
                            from ray_tpu.util.flight_recorder import (
                                ClockOffsetEstimator,
                            )

                            proxy.clock_est = ClockOffsetEstimator()
                        proxy.clock_est.add_ping(
                            sent[1], time.time(), payload[1])
            elif tag == "spans":
                self.on_worker_spans(payload[0], payload[1])
            elif tag == "sync":
                self.on_node_sync(proxy, payload[0])
            elif tag == "devents":
                self.publish_direct_events(proxy.hex, payload[0])
            elif tag == "cevents":
                self.record_cluster_events(payload[0])
            elif tag == "refs":
                self.on_ref_report(payload[0], payload[1])
            elif tag == "store_info_rep":
                req_id, infos = payload
                slot = self._store_info_pending.get(req_id)
                if slot is not None:
                    slot[1] = infos
                    slot[0].set()
            elif tag == "stack_rep":
                req_id, stacks = payload
                slot = self._stack_pending.get(req_id)
                if slot is not None:
                    slot[1] = stacks
                    slot[0].set()
            elif tag == "sealed_payload":
                self.on_sealed_payload(*payload)
            elif tag == "pub1":
                self.publish_oneway(*payload)
            elif tag == "req":
                req_id, op, args = payload
                if op == "worker_rpc" and args and args[0] == "pub_poll":
                    # parked subscriber polls must not occupy the bounded
                    # daemon-request pool
                    threading.Thread(
                        target=self._handle_daemon_req,
                        args=(proxy, req_id, op, args), daemon=True,
                        name="pub-poll").start()
                else:
                    self._daemon_pool.submit(self._handle_daemon_req, proxy,
                                             req_id, op, args)

    def _handle_daemon_req(self, proxy, req_id: int, op: str, args) -> None:
        try:
            # chaos point: "head.daemon_req[.<op>]=drop@N" strands this
            # round-trip ON PURPOSE — the injected fault IS the missing
            # reply; the daemon's bounded rounds re-issue the request
            if fault_injection.fire("head.daemon_req", op) == "drop":
                return  # graftlint: ignore[reply-completeness]
            if op != "worker_rpc":  # worker_rpc counts inside its handler
                self._count_head_rpc(op)
            if op == "locate":
                result = self._locate_for_daemon(*args)
            elif op == "wait_objects":
                result = self.wait_objects(*args)
            elif op == "worker_rpc":
                result = self.handle_worker_rpc(None, None, args[0], args[1])
            elif op == "drop_location":
                oid, node_hex = args
                self.gcs.remove_object_location(oid, node_hex)
                result = None
            else:
                raise ValueError(f"unknown daemon req {op!r}")
            proxy._send("rep", req_id, True, result)
        except Exception as e:  # noqa: BLE001
            proxy._send("rep", req_id, False, e)

    def _locate_for_daemon(self, oid: ObjectID, timeout: float):
        """One bounded wait round of the daemon's object-location loop.

        Small objects on local nodes are returned inline (saves a pull
        round-trip — the analog of inline returns <100KB); otherwise the
        daemon gets object-server addresses to pull from directly.
        """
        cfg = global_config()
        deadline = time.monotonic() + timeout
        attempted_reconstruction = False
        while True:
            with self._lock:
                locs = [h for h in self.gcs.get_object_locations(oid)
                        if h in self.nodes]
                nodes = [self.nodes[h] for h in locs]
            addrs = []
            for h, n in zip(locs, nodes):
                if self._is_local(n):
                    meta = n.store.read_meta(oid)
                    if meta and meta[0] <= cfg.max_direct_call_object_size:
                        try:
                            data, is_err = n.store.get_payload(oid)
                            return ("inline", bytes(data), is_err)
                        except ObjectLostError:
                            continue
                    srv = getattr(n, "object_server", None)
                    if srv is not None:
                        addrs.append((h, srv.address))
                else:
                    addrs.append((h, n.object_addr))
            if addrs:
                return ("locs", addrs)
            if not attempted_reconstruction and not locs:
                attempted_reconstruction = self._maybe_reconstruct(oid)
            with self._object_cv:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return ("timeout",)
                self._object_cv.wait(min(remaining, 0.2))

    def _pull_from_proxy(self, proxy: "NodeProxy", oid: ObjectID, dest_store):
        """Pull an object from one remote node directly into ``dest_store``
        (pooled + arena-direct; driver memory never holds the payload)."""
        return self._pull_from_proxies([proxy], oid, dest_store)

    def _pull_from_proxies(self, proxies, oid: ObjectID, dest_store):
        """Pull from any/all of several remote holders into ``dest_store``
        — striped across peers when the object is large and >=2 have it.
        Holders that failed (even when failover succeeded) lose their
        location entry so future pulls stop dialing them. Returns
        ("inline", bytes, is_err) or ("arena", off, size, is_err)."""
        from .object_transfer import pull_object_striped

        addr_to_hex = {tuple(p.object_addr): p.hex for p in proxies}
        failed: list = []
        res = pull_object_striped([p.object_addr for p in proxies],
                                  self._cluster_key, oid,
                                  dest_store=dest_store,
                                  on_peer_failed=failed.append)
        for a in failed:
            h = addr_to_hex.get(tuple(a))
            if h is not None:
                self.gcs.remove_object_location(oid, h)
        if res is None:
            raise ObjectLostError(oid, "remote node no longer has the object")
        body, is_err = res
        if isinstance(body, tuple):
            _, off, size = body
            return ("arena", off, size, is_err)
        return ("inline", body, is_err)

    def remove_node(self, node_hex: str) -> None:
        """Simulate/handle node death (reference: gcs_node_manager node death
        broadcast + object/actor failover)."""
        with self._lock:
            node = self.nodes.pop(node_hex, None)
        if node is None:
            return
        self.scheduler.remove_node(node_hex)
        self.gcs.mark_node_dead(node_hex)
        from ray_tpu.util import events as events_mod

        events_mod.emit("WARNING", events_mod.SOURCE_NODE,
                        f"node {node_hex[:8]} dead", entity_id=node_hex)
        if self._node_listener is not None:
            self._broadcast_cluster_view()
        node.shutdown()
        self._fail_node_workloads(node_hex, "node died")

    # ------------------------------------------------------------ bounce

    def bounce(self) -> None:
        """Chaos harness: crash-and-restart the head's control plane in
        place (the closest a head-in-driver architecture gets to killing
        the GCS process; reference: GCS FT drills restart the gcs_server
        while raylets and workers keep running).

        What "dies": the daemon join endpoint and every daemon link
        (closed abruptly — no shutdown handshake), plus all daemon-
        derived soft state (load views, lease views, ref reports, the
        proxies themselves and their scheduler entries). What a real
        restart would reload from the journal is reloaded from the
        journal (``GCS.reload_from_store``), including the deferred-
        delete set and last-known lease views. Driver-owned state (the
        owner-side ref/pin/stream tables of PR 7, head-path task
        records) survives by design — the driver process IS the owner
        and its state never lived in the GCS-analog tables.

        Recovery: the endpoint reopens on the SAME port; daemons detect
        the EOF (or a stale epoch on their next sync), re-dial, and
        re-register under their existing hex with a full replay snapshot
        (store manifest, holder leases, hosted actors) plus their
        reliable-message outbox. Daemons that never return are declared
        dead after ``daemon_rejoin_grace_s`` and fail over normally."""
        if self._stopped:
            return
        from ray_tpu.util import events as events_mod

        events_mod.emit("WARNING", events_mod.SOURCE_NODE,
                        "head bounce injected: control plane restarting")
        addr = self.node_server_address
        listener, self._node_listener = self._node_listener, None
        with self._lock:
            proxies = [n for n in self.nodes.values()
                       if not self._is_local(n)]
            for p in proxies:
                self.nodes.pop(p.hex, None)
            self._rejoin_pending.update(p.hex for p in proxies)
            self.node_loads.clear()
            self._ref_reports.clear()
            self._memory_table_cache = (0.0, None)
        if listener is not None:
            from .protocol import close_listener

            close_listener(listener)
        for p in proxies:
            p.alive = False
            self._fail_store_info_waiters(p.hex)
            self._fail_stack_waiters(p.hex)
            try:
                p.channel.close()
            except Exception:
                pass
            self.scheduler.remove_node(p.hex)
        # run off recovered state, not off surviving process memory —
        # this is the honest half of the persistence test
        self.gcs.reload_from_store()
        if self.gcs._durable:
            with self._lock:
                self._deferred_deletes = {
                    ObjectID(b)
                    for b in self.gcs.meta.get("deferred_deletes", ())}
                self._daemon_leases = {
                    h: {ObjectID(b) for b in oids}
                    for h, oids in (self.gcs.meta.get("daemon_leases")
                                    or {}).items()}
        self.epoch += 1
        self.gcs.set_meta("epoch", self.epoch)
        if addr is not None and self._cluster_key is not None:
            from .protocol import make_listener

            new_listener = make_listener(tuple(addr), self._cluster_key)
            self._node_listener = new_listener
            self.node_server_address = new_listener.address
            self._spawn_service(
                lambda: self._node_accept_loop(new_listener), "node-server")
        if proxies:
            self._spawn_rejoin_reaper()
        with self._object_cv:
            self._object_cv.notify_all()

    # ------------------------------------------------------------ submission

    def submit_spec(self, spec: TaskSpec) -> None:
        rec = TaskRecord(spec)
        with self._lock:
            self.tasks[spec.task_id] = rec
            for oid in spec.pinned_args:  # keep promoted args alive
                self.ref_counts[oid] += 1
        self._record_event(spec, "PENDING")
        if spec.actor_id is not None and not spec.is_actor_creation:
            self._submit_actor_task(rec)
        else:
            self._resolve_then_queue(rec)

    def _resolve_then_queue(self, rec: TaskRecord) -> None:
        spec = rec.spec
        missing = set()
        with self._lock:
            for oid in spec.arg_object_ids():
                if not self.gcs.get_object_locations(oid):
                    missing.add(oid)
            if missing:
                rec.state = "WAITING_DEPS"
                rec.missing_deps = missing
                for oid in missing:
                    self._waiting_on[oid].add(spec.task_id)
                return
            rec.state = "QUEUED"
        if (spec.locality_hex is None and spec.actor_id is None
                and spec.scheduling_strategy.kind == "DEFAULT"):
            counts: Dict[str, int] = {}
            for oid in spec.arg_object_ids():
                h = self.locate_large_object(oid)
                if h:
                    counts[h] = counts.get(h, 0) + 1
            if counts:
                spec.locality_hex = max(counts, key=lambda k: counts[k])
        self.scheduler.submit(spec)

    def _submit_actor_task(self, rec: TaskRecord) -> None:
        spec = rec.spec
        with self._lock:
            arec = self.actors.get(spec.actor_id)
            if arec is None:
                self._fail_task_now(rec, ActorDiedError(spec.actor_id, "unknown actor"))
                return
            if arec.state == "DEAD":
                self._fail_task_now(
                    rec, ActorDiedError(spec.actor_id, arec.death_cause or "actor is dead")
                )
                return
            if arec.state in ("PENDING_CREATION", "RESTARTING"):
                arec.pending.append(spec)
                return
            arec.inflight.add(spec.task_id)
            node = self.nodes.get(arec.node_hex)
            worker_id = arec.worker_id
        rec.state = "RUNNING"
        rec.node_hex = arec.node_hex
        rec.worker_id = worker_id
        self._inject_delay("actor_dispatch")
        if node is None or not node.dispatch_to_worker(worker_id, spec):
            self._handle_task_failure(
                rec, ActorDiedError(spec.actor_id, format_death_cause(
                    "actor node/worker gone", rec.node_hex)),
                results=None)

    def create_actor(self, spec: TaskSpec, name: Optional[str], namespace: str,
                     max_restarts: int, detached: bool,
                     max_task_retries: int = 0) -> None:
        arec = ActorRecord(spec.actor_id, creation_spec=spec, max_restarts=max_restarts)
        with self._lock:
            self.actors[spec.actor_id] = arec
        # detached actors journal their pickled creation spec: a restarted
        # head re-creates them from it (reference: GCS FT replays the
        # actor table and reconstructs detached actors). Non-detached
        # actors die with their owner, so the spec would be dead weight.
        spec_bytes = pickle.dumps(spec) if detached else None
        self.gcs.register_actor(ActorInfo(
            actor_id=spec.actor_id, name=name, namespace=namespace,
            class_name=spec.function_name, state="PENDING_CREATION",
            max_restarts=max_restarts, detached=detached,
            creation_spec=spec_bytes,
            max_task_retries=max_task_retries,
        ))
        self.submit_spec(spec)

    # ------------------------------------------------------------ dispatch cb

    def _dispatch_to_node(self, node_hex: str, spec: TaskSpec, binding: dict) -> None:
        with self._lock:
            rec = self.tasks.get(spec.task_id)
            node = self.nodes.get(node_hex)
            if rec is not None and rec.cancelled:
                self.scheduler.release(node_hex, spec, binding)
                return
            if rec is not None:
                rec.state = "RUNNING"
                rec.node_hex = node_hex
                rec.binding = binding
        self._record_event(spec, "RUNNING", node_hex)
        if node is None:
            if rec:
                self._handle_task_failure(rec, WorkerCrashedError("node gone"), None)
            return
        node.dispatch(spec, binding)

    # ------------------------------------------------------------ completion

    def _inject_delay(self, handler: str) -> None:
        """Fault-injection latency (reference: RAY_testing_asio_delay_us,
        ray_config_def.h:821): RAY_TPU_TESTING_DELAY_MS="name=ms,..."."""
        d = global_config().delay_for(handler)
        if d:
            time.sleep(d)

    def _count_head_rpc(self, op: str) -> None:
        """Every control RPC the head serves increments
        ``ray_tpu_head_rpcs_total{op=}`` — the head-freeness gate:
        steady-state direct actor calls and stream consumption must keep
        this counter flat. Doubles as the ``RAY_TPU_TEST_HEAD_DELAY_MS``
        injection point: slowing the head's control loop here must not
        move direct-path latency/throughput (bench_core --actor-bench)."""
        from ray_tpu.util.metrics import registry

        registry().record("ray_tpu_head_rpcs_total", "counter",
                          "control RPCs served by the head process",
                          (("op", op),), 1.0, mode="add")
        d = global_config().test_head_delay_ms
        if d:
            time.sleep(d / 1000.0)

    def _begin_settle(self, rec: TaskRecord) -> bool:
        """Claim the right to settle this attempt; False if another path
        (completion vs crash-report race) already did."""
        with self._lock:
            if rec.settling or rec.state in ("FAILED", "FINISHED"):
                return False
            rec.settling = True
            return True

    def _release_task_resources(self, rec: TaskRecord, fallback_hex: str,
                                node_binding, err_name):
        """Idempotent resource release; returns the lease-cached next task
        (complete_and_next) when this call performed the release."""
        spec = rec.spec
        if not (spec.actor_id is None or spec.is_actor_creation):
            return None
        if spec.is_actor_creation and err_name is None:
            # successful creation keeps its LIFETIME resources; the
            # scheduling-only portion (the implicit CPU) returns now
            self._shrink_actor_reservation(rec, spec)
            return None
        with self._lock:
            if rec.released:
                return None
            rec.released = True
        return self.scheduler.complete_and_next(
            rec.node_hex or fallback_hex, spec,
            rec.binding or node_binding or {})

    def on_task_finished(self, node, task_id: TaskID, err_name: Optional[str],
                         node_spec: Optional[TaskSpec], node_binding: Optional[dict],
                         results: List[Tuple[ObjectID, Optional[bytes], bool]],
                         worker_id: Optional[WorkerID] = None,
                         attempt: Optional[int] = None) -> None:
        with self._lock:
            rec = self.tasks.get(task_id)
        if rec is None:
            self._seal_results(node, results)
            return
        self._inject_delay("task_finished")
        # A finish arriving for a SUPERSEDED attempt (the crash handler
        # already settled + released + re-queued this record — its retry
        # reset the guards) must be dropped entirely: releasing again would
        # inflate scheduler availability and settling would seal stale
        # results over the retried attempt. Detect it by retry-in-progress
        # states and, across a pickle boundary (remote nodes), the attempt
        # number the node dispatched.
        with self._lock:
            retry_pending = rec.state in ("PENDING", "QUEUED",
                                          "WAITING_DEPS")
            # attempt stamped at dispatch (spec objects mutate on retry):
            # a finish for a superseded attempt is dropped even if the
            # retry already reached RUNNING
            if attempt is not None and attempt != rec.spec.attempt:
                retry_pending = True
        if retry_pending:
            return
        # Release resources for non-actor-method tasks (idempotent — the
        # crash path may have released already). A successful actor
        # creation keeps its resources for the actor's lifetime. The
        # release runs through the lease-caching fast path: the next
        # queued same-shape task comes back placed and is dispatched below
        # on this same (node-reader) thread.
        next_placed = self._release_task_resources(rec, node.hex,
                                                   node_binding, err_name)
        try:
            if self._begin_settle(rec):
                self._settle_finished(rec, node, task_id, err_name, results,
                                      worker_id)
            else:
                # crash handler settled this attempt first: results arriving
                # late are dropped (it retried or failed the task)
                pass
        finally:
            if next_placed is not None:
                self._dispatch_to_node(*next_placed)

    def _settle_finished(self, rec: TaskRecord, node, task_id, err_name,
                         results, worker_id) -> None:
        spec = rec.spec
        if rec.cancelled:
            # already sealed TaskCancelledError; drop the late results
            return
        if err_name is not None:
            retriable = self._is_retriable(spec, err_name)
            if retriable:
                self._retry_task(rec, results)
                return
            rec.state = "FAILED"
            rec.settled_at = time.monotonic()
            self._unpin_args(rec)
            self._record_event(spec, "FAILED", node.hex, error=err_name)
            self._seal_results(node, results)
            if spec.is_actor_creation:
                self._on_actor_creation_failed(spec, err_name)
            self._after_seal(spec)
            return
        rec.state = "FINISHED"
        rec.settled_at = time.monotonic()
        self._unpin_args(rec)
        self._record_event(spec, "FINISHED", node.hex)
        self._seal_results(node, results)
        if spec.is_actor_creation:
            self._on_actor_alive(spec, node, worker_id)
        if spec.actor_id is not None and not spec.is_actor_creation:
            with self._lock:
                arec = self.actors.get(spec.actor_id)
                if arec:
                    arec.inflight.discard(task_id)
        self._after_seal(spec)

    def _seal_results(self, node, results) -> None:
        # Remote (proxy) nodes have no in-process store: inline results ride
        # the control channel and land in the head node's store (the analog
        # of the owner's in-process memory store).
        is_proxy = not hasattr(node, "store")
        store_node = self.head_node if is_proxy else node
        for oid, payload, is_error in results:
            if payload is not None:
                store_node.store.put_inline(oid, payload, is_error)
                # location = where the bytes actually are: inline results
                # from a proxy node live only in the head store
                self.on_object_sealed(oid, store_node.hex)
            else:
                self.on_object_sealed(oid, node.hex)

    def _after_seal(self, spec: TaskSpec) -> None:
        self.scheduler.kick()

    def _is_retriable(self, spec: TaskSpec, err_name: str) -> bool:
        if spec.attempt >= spec.max_retries:
            return False
        system_errors = ("WorkerCrashedError", "NodeDiedError", "ActorDiedError")
        if err_name in system_errors:
            return spec.actor_id is None or spec.is_actor_creation
        return spec.retry_exceptions

    def _retry_task(self, rec: TaskRecord, results) -> None:
        cfg = global_config()
        spec = rec.spec
        spec.attempt += 1
        rec.state = "PENDING"
        rec.node_hex = None
        rec.binding = None
        rec.settling = False
        rec.released = False
        self._record_event(spec, "RETRY")
        delay = cfg.task_retry_delay_ms / 1000.0

        def _resubmit():
            if delay:
                time.sleep(delay)
            if spec.actor_id is not None and not spec.is_actor_creation:
                self._submit_actor_task(rec)
            else:
                self._resolve_then_queue(rec)

        threading.Thread(target=_resubmit, daemon=True).start()

    def _unpin_args(self, rec: TaskRecord) -> None:
        """Release arg pins once the task settles for good."""
        to_delete = []
        with self._lock:
            if rec.unpinned or not rec.spec.pinned_args:
                return
            rec.unpinned = True
            for oid in rec.spec.pinned_args:
                self.ref_counts[oid] -= 1
                if self.ref_counts[oid] <= 0:
                    to_delete.append(oid)
        if not self._stopped:
            for oid in to_delete:
                self.delete_object(oid)

    def _fail_task_now(self, rec: TaskRecord, exc: Exception,
                       _guard: bool = True) -> None:
        if _guard and not self._begin_settle(rec):
            return
        rec.state = "FAILED"
        rec.settled_at = time.monotonic()
        self._unpin_args(rec)
        err = exc if isinstance(exc, (ActorDiedError, TaskCancelledError, ObjectLostError)) \
            else TaskError.from_exception(rec.spec.function_name, exc)
        payload = serialization.serialize(err).to_bytes()
        node = self.head_node
        for oid in rec.spec.return_ids():
            node.store.put_inline(oid, payload, is_error=True)
            self.on_object_sealed(oid, node.hex)

    def _handle_task_failure(self, rec: TaskRecord, exc: Exception, results) -> None:
        spec = rec.spec
        next_placed = self._release_task_resources(
            rec, rec.node_hex or "", None, type(exc).__name__)
        if not self._begin_settle(rec):
            # the completion path settled this attempt first
            if next_placed is not None:
                self._dispatch_to_node(*next_placed)
            return
        if self._is_retriable(spec, type(exc).__name__):
            self._retry_task(rec, results)
        else:
            self._record_event(spec, "FAILED", rec.node_hex, error=str(exc))
            self._fail_task_now(rec, exc, _guard=False)
            if spec.is_actor_creation:
                self._on_actor_creation_failed(spec, str(exc))
        if next_placed is not None:
            self._dispatch_to_node(*next_placed)

    # ------------------------------------------------------------ actors

    def _on_actor_alive(self, spec: TaskSpec, node,
                        worker_id: Optional[WorkerID] = None) -> None:
        flush = []
        with self._lock:
            arec = self.actors.get(spec.actor_id)
            if arec is None:
                return
            arec.state = "ALIVE"
            arec.node_hex = node.hex
            if worker_id is not None:
                arec.worker_id = worker_id
            elif hasattr(node, "_workers"):
                with node._lock:
                    for w in node._workers.values():
                        if w.actor_id == spec.actor_id:
                            arec.worker_id = w.worker_id
                            break
            while arec.pending:
                flush.append(arec.pending.popleft())
        self.gcs.update_actor(spec.actor_id, state="ALIVE", node_hex=node.hex)
        for mspec in flush:
            rec = self.tasks.get(mspec.task_id)
            if rec is not None:
                self._submit_actor_task(rec)

    def _on_actor_creation_failed(self, spec: TaskSpec, cause: str) -> None:
        with self._lock:
            arec = self.actors.get(spec.actor_id)
            if arec is None:
                return
            arec.state = "DEAD"
            arec.death_cause = f"creation failed: {cause}"
            pending = list(arec.pending)
            arec.pending.clear()
        self.gcs.update_actor(spec.actor_id, state="DEAD", death_cause=cause)
        self.gcs.remove_actor_name(spec.actor_id)
        for mspec in pending:
            rec = self.tasks.get(mspec.task_id)
            if rec is not None:
                self._fail_task_now(rec, ActorDiedError(spec.actor_id, arec.death_cause))

    def _shrink_actor_reservation(self, rec: TaskRecord, spec: TaskSpec) -> None:
        """Release the scheduling-only part of an actor's reservation
        (reference semantics: a default actor needs 1 CPU to be placed but
        holds 0 CPUs while alive — ray_option_utils actor defaults)."""
        from .resources import ResourceSet

        retained = spec.retained_resources
        if retained is None:
            return
        with self._lock:
            # released: an actor-death release raced ahead of this
            # creation-success settle and already returned the FULL
            # reservation — crediting the delta again would let the
            # scheduler over-commit the node
            if rec.shrunk or rec.released:
                return
            rec.shrunk = True
        delta = {k: v - retained._map.get(k, 0)
                 for k, v in spec.resources._map.items()
                 if v - retained._map.get(k, 0) > 0}
        if not delta:
            return
        self.scheduler.release_partial(
            rec.node_hex or "", spec, ResourceSet._from_fixed_map(delta),
            binding=None)  # unit-instance resources are always retained

    def _actor_release_set(self, crec: Optional[TaskRecord], cspec: TaskSpec):
        """What an actor's death/restart must return: the retained set if
        the scheduling-only portion was already released, else the full
        creation reservation."""
        if (crec is not None and crec.shrunk
                and cspec.retained_resources is not None):
            return cspec.retained_resources
        return cspec.resources

    def _release_actor_creation(self, arec: ActorRecord) -> None:
        """Return a dead/restarting actor's reservation to its node or PG
        bundle — exactly once per incarnation (graceful exit, kill, crash,
        and restart paths all funnel here)."""
        cspec = arec.creation_spec
        if cspec is None:
            return
        crec = self.tasks.get(cspec.task_id)
        if crec is None:
            return
        with self._lock:
            if crec.released:
                return
            crec.released = True
        self.scheduler.release_partial(
            crec.node_hex or "", cspec,
            self._actor_release_set(crec, cspec), crec.binding or {})

    def _handle_actor_failure(self, arec: ActorRecord, cause: str) -> None:
        """Worker/node hosting the actor died (reference: ReconstructActor)."""
        with self._lock:
            if arec.state == "DEAD":
                return
            restart = arec.num_restarts < arec.max_restarts or arec.max_restarts == -1
            inflight = list(arec.inflight)
            arec.inflight.clear()
            # retained through RESTARTING too: calls failing against the
            # down incarnation attribute the LAST observed death
            arec.death_cause = cause
            if restart:
                arec.state = "RESTARTING"
                arec.num_restarts += 1
            else:
                arec.state = "DEAD"
                pending = list(arec.pending)
                arec.pending.clear()
        # fail in-flight method calls (they may be retried onto the new
        # incarnation per max_task_retries -> retry_exceptions semantics)
        for tid in inflight:
            rec = self.tasks.get(tid)
            if rec is not None and rec.state == "RUNNING":
                if not self._begin_settle(rec):
                    continue  # completion path settled this attempt first
                if rec.spec.max_retries > rec.spec.attempt and rec.spec.retry_exceptions:
                    self._retry_task(rec, None)
                else:
                    self._fail_task_now(
                        rec, ActorDiedError(arec.actor_id, cause,
                                            restarting=restart),
                        _guard=False)
        if restart:
            self.gcs.update_actor(arec.actor_id, state="RESTARTING",
                                  death_cause=cause,
                                  num_restarts=arec.num_restarts)
            # release old incarnation's resources and resubmit creation
            self._release_actor_creation(arec)
            cspec = arec.creation_spec
            import copy

            new_spec = copy.deepcopy(cspec)
            new_spec.task_id = TaskID.from_random()
            arec.creation_spec = new_spec
            with self._lock:
                self.tasks[new_spec.task_id] = TaskRecord(new_spec)
            self._resubmit_after_backoff(self.tasks[new_spec.task_id],
                                         arec.num_restarts)
        else:
            self.gcs.update_actor(arec.actor_id, state="DEAD", death_cause=cause)
            self.gcs.remove_actor_name(arec.actor_id)
            # a killed/crashed actor's reservation must come back (this
            # branch previously leaked it)
            self._release_actor_creation(arec)
            for mspec in pending:
                rec = self.tasks.get(mspec.task_id)
                if rec is not None:
                    self._fail_task_now(rec, ActorDiedError(arec.actor_id, cause))

    @staticmethod
    def _restart_backoff_s(num_restarts: int) -> float:
        """Exponential re-creation backoff: the Nth restart waits
        base * 2^(N-1), capped (reference: gcs_actor_manager backoff —
        a crash-looping actor must not monopolize the scheduler)."""
        cfg = global_config()
        base = cfg.actor_restart_delay_ms
        if base <= 0 or num_restarts <= 0:
            return 0.0
        delay = base * (2 ** (num_restarts - 1))
        return min(delay, cfg.actor_restart_max_delay_ms) / 1000.0

    def _resubmit_after_backoff(self, rec: TaskRecord,
                                num_restarts: int) -> None:
        delay = self._restart_backoff_s(num_restarts)
        if delay <= 0:
            self._resolve_then_queue(rec)
            return

        def run():
            # pace on the stop event so shutdown never waits out a backoff
            if not self._stop_event.wait(delay) and not self._stopped:
                self._resolve_then_queue(rec)

        threading.Thread(target=run, daemon=True,
                         name="actor-restart-backoff").start()

    def actor_location(self, actor_id: ActorID) -> Optional[dict]:
        """Direct-actor-path resolve: owners ask once per incarnation and
        then call the actor's node directly (reference: the actor-table
        subscription ActorTaskSubmitter uses for its cached RPC address)."""
        with self._lock:
            arec = self.actors.get(actor_id)
            if arec is None:
                return None
            return {"state": arec.state, "node_hex": arec.node_hex,
                    "death_cause": arec.death_cause,
                    "num_restarts": arec.num_restarts}

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        with self._lock:
            arec = self.actors.get(actor_id)
            if arec is None:
                return
            if no_restart:
                arec.max_restarts = arec.num_restarts  # exhaust restarts
            node = self.nodes.get(arec.node_hex)
            worker_id = arec.worker_id
        if node is not None and worker_id is not None:
            node.kill_worker(worker_id)
        # crash path in node reader will drive _handle_actor_failure

    # ------------------------------------------------------------ worker events

    def _retire_worker_metrics(self, node, w) -> None:
        from ray_tpu.util.metrics import registry

        registry().retire(f"{node.hex[:6]}:{w.pid}")
        with self._lock:
            self._ref_reports.pop(f"{node.hex[:6]}:{w.pid}", None)

    def on_worker_exit(self, node: Node, w: WorkerHandle) -> None:
        """Graceful actor termination (__ray_terminate__)."""
        self._retire_worker_metrics(node, w)
        if w.actor_id is not None:
            with self._lock:
                arec = self.actors.get(w.actor_id)
                if arec is not None:
                    arec.state = "DEAD"
                    arec.death_cause = format_death_cause(
                        "actor exited gracefully", node.hex, w.pid)
                    pending = list(arec.pending)
                    arec.pending.clear()
                else:
                    pending = []
            self.gcs.update_actor(w.actor_id, state="DEAD",
                                  death_cause="exited gracefully")
            self.gcs.remove_actor_name(w.actor_id)
            if arec is not None:
                self._release_actor_creation(arec)
            for mspec in pending:
                rec = self.tasks.get(mspec.task_id)
                if rec is not None:
                    self._fail_task_now(rec, ActorDiedError(w.actor_id, "actor exited"))

    def on_worker_crashed(self, node: Node, w: WorkerHandle,
                          spec: Optional[TaskSpec], binding: Optional[dict],
                          prev_state: str) -> None:
        if self._stopped or not node.alive:
            return
        self._inject_delay("worker_crashed")
        self._retire_worker_metrics(node, w)
        if w.actor_id is not None:
            with self._lock:
                arec = self.actors.get(w.actor_id)
            if arec is not None:
                self._handle_actor_failure(arec, format_death_cause(
                    "actor worker process died", node.hex, w.pid))
            return
        if spec is not None:
            rec = self.tasks.get(spec.task_id)
            if rec is not None:
                self._handle_task_failure(
                    rec, WorkerCrashedError(
                        f"worker pid={w.pid} died executing {spec.function_name}"),
                    None)

    # ------------------------------------------------------------ objects

    def on_object_sealed(self, oid: ObjectID, node_hex: str) -> None:
        self.gcs.add_object_location(oid, node_hex)
        for e in list(self._seal_events):
            e.set()
        waiters: List[TaskID] = []
        with self._object_cv:
            if oid in self._waiting_on:
                for tid in self._waiting_on.pop(oid):
                    rec = self.tasks.get(tid)
                    if rec is None:
                        continue
                    rec.missing_deps.discard(oid)
                    if not rec.missing_deps and rec.state == "WAITING_DEPS":
                        waiters.append(tid)
            self._object_cv.notify_all()
        for tid in waiters:
            rec = self.tasks.get(tid)
            rec.state = "QUEUED"
            self.scheduler.submit(rec.spec)

    def state_list(self, kind: str, limit: int = 1000):
        """State API backend (reference: python/ray/util/state/api.py)."""
        gcs = self.gcs
        if kind == "tasks":
            latest: Dict[bytes, dict] = {}
            for ev in list(gcs.task_events):
                latest[ev.task_id] = {
                    "task_id": ev.task_id.hex(), "name": ev.name,
                    "state": ev.state, "node_hex": ev.node_hex,
                    "ts": ev.ts, "attempt": ev.attempt, "error": ev.error,
                }
            return list(latest.values())[-limit:]
        if kind == "actors":
            return [{
                "actor_id": a.actor_id.hex(), "class_name": a.class_name,
                "state": a.state, "name": a.name,
                "node_hex": getattr(a, "node_hex", None),
            } for a in list(gcs.actors.values())[:limit]]
        if kind == "nodes":
            return [{
                "node_id": n.hex, "alive": n.Alive
                if hasattr(n, "Alive") else n.alive,
                "resources": n.resources_total, "labels": n.labels,
                "load": self.node_loads.get(n.hex),
            } for n in list(gcs.nodes.values())[:limit]]
        if kind == "objects":
            # rewritten rows (the `ray list objects` analog): size, owner,
            # age, ref-type counts, spilled flag — from the joined
            # ownership table, with the legacy ref_count field kept
            rows = self.memory_table(limit=limit, timeout=0.5)
            for r in rows:
                r["locations"] = sorted(r["locations"])
                r["owner"] = r.pop("creator", None) or "driver"
                r["ref_count"] = r["pinned"]
            return rows
        if kind == "memory":
            return self.memory_table(limit=limit, timeout=1.5)
        if kind == "task_events":
            # FULL event log (not latest-state-only): worker/client
            # drivers rebuild real durations from RUNNING->terminal pairs
            # (util/timeline.py)
            return [{
                "task_id": ev.task_id.hex(), "name": ev.name,
                "state": ev.state, "node_hex": ev.node_hex, "ts": ev.ts,
                "attempt": ev.attempt, "error": ev.error,
            } for ev in list(gcs.task_events)[-limit:]]
        if kind == "placement_groups":
            return [{"pg_id": pid.hex(), "state": pg.state,
                     "bundles": len(pg.bundles)}
                    for pid, pg in
                    list(self.scheduler._pgs.items())[:limit]]
        if kind == "cluster_events":
            return gcs.list_cluster_events(limit)
        raise ValueError(f"unknown state kind {kind!r}")

    def on_worker_metrics(self, source_id: str, snapshot: dict) -> None:
        from ray_tpu.util.metrics import registry

        registry().merge(source_id, snapshot)

    def on_worker_spans(self, source_id: str, payload: dict) -> None:
        """A drained flight-recorder batch from a worker or daemon
        (one-way, droppable — spans are observability, not state)."""
        q = self.flight_spans.get(source_id)
        if q is None:
            q = self.flight_spans[source_id] = deque(maxlen=256)
        q.append(payload)

    def on_worker_log(self, node_hex: str, pid: int, text: str) -> None:
        """Tail-to-driver (reference: log_monitor.py -> driver stdout)."""
        if not global_config().log_to_driver:
            return
        prefix = f"({node_hex[:6]} pid={pid}) "
        for line in text.splitlines():
            print(prefix + line, file=sys.stderr)

    def start_metrics_server(self, host: str = "127.0.0.1", port: int = 0):
        """Prometheus text endpoint (reference: metrics agent re-export)."""
        import http.server

        from ray_tpu.util.metrics import registry, render_prometheus

        if getattr(self, "_metrics_server", None) is not None:
            return self._metrics_address

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(handler):  # noqa: N805
                body = render_prometheus(registry()).encode()
                handler.send_response(200)
                handler.send_header("Content-Type",
                                    "text/plain; version=0.0.4")
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        srv = http.server.ThreadingHTTPServer((host, port), Handler)
        self._metrics_server = srv
        self._metrics_address = srv.server_address
        self._spawn_service(srv.serve_forever, "metrics-http")
        return self._metrics_address

    def on_stream_item(self, task_id: TaskID, index: int) -> None:
        """A streaming task sealed item ``index`` (reference: streaming
        generator item report). The item gets an owner pin (same semantics
        as worker register_owned_object) so the reclaim loop can't evict
        it before the consumer reads it; stream/task records are retained
        until shutdown (task GC is future work, as for task records)."""
        with self._object_cv:
            cur = self.streams.get(task_id, 0)
            if index + 1 > cur:
                self.streams[task_id] = index + 1
                self.ref_counts[ObjectID.for_stream(task_id, index)] += 1
            self._object_cv.notify_all()

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: Optional[float]):
        """Next-item protocol for HEAD-PATH streams (tasks the head
        scheduled and records): ("item", oid) | ("end", total) |
        ("error",) | ("wait",) after ``timeout``. Direct-path streams
        never come here — their consumers subscribe to the owner over
        the stream_sub reply chain."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._object_cv:
                count = self.streams.get(task_id, 0)
                rec = self.tasks.get(task_id)
                if index < count:
                    return ("item", ObjectID.for_stream(task_id, index))
                if rec is None:
                    if task_id not in self.streams:
                        # no record and no items: the stream is not (or
                        # no longer) known here — GC'd or never head-path
                        return ("error",)
                    # record folded but items remain (GC kept the pins):
                    # everything announced was already consumed
                    return ("end", count)
                elif rec.state == "FAILED" or rec.cancelled:
                    return ("error",)
                elif rec.state == "FINISHED":
                    return ("end", count)
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return ("wait",)
                self._object_cv.wait(min(remaining, 0.2)
                                     if remaining is not None else 0.2)

    def get_object_payload(self, oid: ObjectID, timeout: Optional[float]):
        """Driver-side read: returns (buffer, is_error). Blocks until sealed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        attempted_reconstruction = False
        while True:
            with self._lock:
                locs = self.gcs.get_object_locations(oid)
                node = None
                remote = []
                for h in locs:
                    cand = self.nodes.get(h)
                    if cand is None:
                        continue
                    if self._is_local(cand):
                        node = cand  # prefer a local (zero-copy) location
                    else:
                        remote.append(cand)
            if node is not None:
                try:
                    return node.store.get_payload(oid)
                except ObjectLostError:
                    self.gcs.remove_object_location(oid, node.hex)
                    continue
            if remote:
                # remote daemon(s): pooled chunked pull — striped across
                # holders when several have it; large payloads land in the
                # head node's store (cached location for future reads)
                try:
                    rep = self._pull_from_proxies(remote, oid,
                                                  self.head_node.store)
                except ObjectLostError:
                    for n in remote:
                        self.gcs.remove_object_location(oid, n.hex)
                    continue
                if rep[0] == "inline":
                    return rep[1], rep[2]
                self.on_object_sealed(oid, self.head_node.hex)
                return self.head_node.store.get_payload(oid)
            # no live location: try lineage reconstruction once
            if not attempted_reconstruction and locs == set():
                if self._maybe_reconstruct(oid):
                    attempted_reconstruction = True
            with self._object_cv:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"get() timed out on {oid.hex()}")
                self._object_cv.wait(min(remaining, 0.2) if remaining else 0.2)

    def _maybe_reconstruct(self, oid: ObjectID) -> bool:
        """Lineage reconstruction (reference: object_recovery_manager.h)."""
        if not global_config().lineage_pinning_enabled:
            return False
        tid = oid.task_id()
        rec = self.tasks.get(tid)
        if rec is None:
            # no head record: a direct-path result. The driver owner's
            # lineage table can resubmit it (worker-owned results recover
            # in the worker's own get path; a third process pulling a
            # worker-owned lost object is not recoverable — the reference
            # has the same owner-reachability constraint)
            cb = self.direct_recover
            if cb is not None:
                try:
                    return bool(cb(oid))
                except Exception:
                    return False
            return False
        if rec.state in ("PENDING", "QUEUED", "RUNNING", "WAITING_DEPS"):
            return False
        spec = rec.spec
        if spec.actor_id is not None:
            return False  # actor results are not reconstructable
        spec.attempt += 1
        rec.state = "PENDING"
        self._record_event(spec, "RECONSTRUCTING")
        self._resolve_then_queue(rec)
        return True

    def get_object_for_node(self, node: Node, oid: ObjectID,
                            timeout: Optional[float],
                            hint: Optional[str] = None):
        """Worker get: ensure the object is readable on `node`; return either
        ("inline", bytes, is_err) or ("arena", offset, size, is_err).
        Transfers from a remote node's store when needed (reference:
        object_manager.cc chunked pull). ``hint`` names a node believed to
        hold the object (direct-path owner hint) — consulted when the
        directory has no location yet."""
        deadline = None if timeout is None else time.monotonic() + timeout
        attempted_reconstruction = False
        while True:
            if node.store.contains(oid):
                info = node.store.entry_info(oid)
                if info is None:
                    payload, is_err = node.store.get_payload(oid)
                    return ("inline", bytes(payload), is_err)
                off, size, is_err = info
                return ("arena", off, size, is_err)
            with self._lock:
                locs = [h for h in self.gcs.get_object_locations(oid) if h in self.nodes]
                if not locs and hint and hint in self.nodes:
                    locs = [hint]
            if locs:
                src = self.nodes[locs[0]]
                if not self._is_local(src):
                    try:
                        rep = self._pull_from_proxy(src, oid, node.store)
                    except ObjectLostError:
                        self.gcs.remove_object_location(oid, src.hex)
                        continue
                    if rep[0] == "arena":
                        self.on_object_sealed(oid, node.hex)
                    return rep
                try:
                    payload, is_err = src.store.get_payload(oid)
                except ObjectLostError:
                    continue
                data = bytes(payload)
                if len(data) <= global_config().max_direct_call_object_size:
                    return ("inline", data, is_err)
                off, view = node.store.create(oid, len(data), transfer=True)
                view[: len(data)] = data
                node.store.seal(oid, is_err)
                self.on_object_sealed(oid, node.hex)
                return ("arena", off, len(data), is_err)
            if not attempted_reconstruction and not locs:
                # object lost with its node: lineage reconstruction, same as
                # the driver get path (reference: object_recovery_manager.h)
                if self._maybe_reconstruct(oid):
                    attempted_reconstruction = True
            with self._object_cv:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return ("timeout",)
                self._object_cv.wait(min(remaining, 0.2) if remaining else 0.2)

    def wait_objects(self, oids: List[ObjectID], num_returns: int,
                     timeout: Optional[float],
                     fetch_local: bool = False) -> List[ObjectID]:
        """Readiness = the object exists somewhere; with ``fetch_local``,
        readiness additionally requires local (in-process) availability,
        and the wait itself triggers the pull from remote daemons
        (reference: ray.wait fetch_local semantics)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            to_pull = []
            with self._lock:
                ready = []
                for oid in oids:
                    locs = self.gcs.get_object_locations(oid)
                    if not locs:
                        continue
                    if not fetch_local:
                        ready.append(oid)
                        continue
                    nodes = [self.nodes.get(h) for h in locs]
                    if any(n is not None and self._is_local(n)
                           for n in nodes):
                        ready.append(oid)
                    elif oid not in self._active_pulls:
                        # head-level dedup: concurrent/looping waits share
                        # one in-flight pull per object; a FAILED pull
                        # leaves the set so the next round retries
                        # (possibly from another replica)
                        proxy = next((n for n in nodes if n is not None),
                                     None)
                        if proxy is not None:
                            self._active_pulls.add(oid)
                            to_pull.append((oid, proxy))
            for oid, proxy in to_pull:
                self._spawn_local_pull(oid, proxy)
            if len(ready) >= num_returns:
                return ready[:num_returns]
            with self._object_cv:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return ready
                self._object_cv.wait(min(remaining, 0.2) if remaining else 0.2)

    def _spawn_local_pull(self, oid: ObjectID, proxy) -> None:
        """Background chunked pull into the head store (fetch_local)."""
        def run():
            try:
                rep = self._pull_from_proxy(proxy, oid, self.head_node.store)
                if rep[0] == "inline":
                    self.head_node.store.put_inline(oid, rep[1], rep[2],
                                                    transfer=True)
                self.on_object_sealed(oid, self.head_node.hex)
            except Exception:
                pass  # source lost mid-pull: the wait loop re-locates
            finally:
                with self._lock:
                    self._active_pulls.discard(oid)

        threading.Thread(target=run, daemon=True,
                         name=f"fetch-{oid.hex()[:6]}").start()

    def broadcast_object(self, oid: ObjectID,
                         target_hexes: Optional[List[str]] = None) -> int:
        """Push ``oid`` to every (or the given) alive node via a binomial
        tree rooted at a holder (reference: push_manager.h broadcast; the
        '1 GiB to 50+ nodes' envelope row). Returns the number of targets
        the tree was asked to cover."""
        with self._lock:
            locs = [h for h in self.gcs.get_object_locations(oid)
                    if h in self.nodes]
            if not locs:
                return 0
            holder_hex = next((h for h in locs
                               if self._is_local(self.nodes[h])), locs[0])
            holder = self.nodes[holder_hex]
            targets = []
            for h, n in self.nodes.items():
                if h == holder_hex or h in locs or not n.alive:
                    continue
                if self._is_local(n):
                    srv = getattr(n, "object_server", None)
                    if srv is not None:
                        targets.append((h, tuple(srv.address)))
                else:
                    targets.append((h, tuple(n.object_addr)))
            if target_hexes is not None:
                want = set(target_hexes)
                targets = [t for t in targets if t[0] in want]
        if not targets:
            return 0
        if self._is_local(holder):
            threading.Thread(target=holder.push_object_to,
                             args=(oid, targets), daemon=True,
                             name=f"bcast-{oid.hex()[:6]}").start()
        else:
            holder._send("push_object", oid, targets)
        return len(targets)

    def object_locations(self, oids: List[ObjectID]) -> List[List[str]]:
        """Node hexes holding each object, aligned with ``oids``.

        The block-location lookup behind data-plane locality (executor
        dispatch hints, streaming_split dealers). Unlike
        :meth:`locate_large_object` there is no size filter — callers
        decide whether the bytes are worth chasing."""
        with self._lock:
            return [[h for h in self.gcs.get_object_locations(oid)
                     if h in self.nodes] for oid in oids]

    def locate_large_object(self, oid: ObjectID) -> Optional[str]:
        """Locality signal: hex of a node holding ``oid`` when the bytes
        are big enough to prefer moving the task over the data
        (reference: LocalityAwareLeasePolicy / Data locality_hints)."""
        cfg = global_config()
        with self._lock:
            for h in self.gcs.get_object_locations(oid):
                n = self.nodes.get(h)
                if n is None:
                    continue
                if self._is_local(n):
                    meta = n.store.read_meta(oid)
                    if meta and meta[0] > cfg.max_direct_call_object_size:
                        return h
                    return None  # small object: no locality value
                # daemon-held objects are store-resident (inline results
                # from daemons land in the head store), so large enough
                return h
        return None

    def add_seal_waiter(self, event: threading.Event) -> None:
        self._seal_events.add(event)

    def remove_seal_waiter(self, event: threading.Event) -> None:
        self._seal_events.discard(event)

    def delete_object(self, oid: ObjectID) -> None:
        # owner-side pin guard: an in-flight direct task owned by the
        # driver still needs this object — defer; release_owner_pins
        # (fired on the task-settle reply chain) applies it then
        epc = self.extra_pin_check
        if epc is not None and epc(oid):
            with self._lock:
                self._deferred_deletes.add(oid)
                self._persist_deferred_locked()
            return
        # holder-lease guard: an in-flight WORKER-owned direct task leases
        # its args on the node it flows through — that lease must defer
        # the cluster-wide delete too (the bytes may live on a THIRD node
        # the executor hasn't pulled from yet); release_holder_lease
        # retries when the lease drops at task settle
        with self._lock:
            leased = any(self._is_local(n) and n.has_lease(oid)
                         for n in self.nodes.values())
            if not leased:
                # daemon-held leases arrive on the sync cadence;
                # on_node_sync retries deferred deletes when a lease
                # view drops the oid, remove_node when the daemon dies
                leased = any(oid in ls
                             for ls in self._daemon_leases.values())
            if leased:
                self._deferred_deletes.add(oid)
                self._persist_deferred_locked()
                return
        local_nodes = []
        with self._lock:
            if oid in self._deferred_deletes:
                self._deferred_deletes.discard(oid)
                self._persist_deferred_locked()
            locs = self.gcs.get_object_locations(oid)
            for h in locs:
                node = self.nodes.get(h)
                if node is not None:
                    if self._is_local(node):
                        local_nodes.append(node)
                    else:
                        node.store_delete(oid)
                self.gcs.remove_object_location(oid, h)
        for node in local_nodes:
            # outside the head lock; holder leases may defer the bytes
            node.delete_from_store(oid)

    def release_owner_pins(self, oids) -> None:
        """The driver's direct manager released the last in-flight pin on
        these oids: apply any delete that was deferred behind the pin."""
        for oid in oids:
            with self._lock:
                pending = oid in self._deferred_deletes
                refs = self.ref_counts.get(oid, 0)
            if pending and refs <= 0 and not self._stopped:
                self.delete_object(oid)

    # a node's holder lease releasing retries the same deferred deletes
    release_holder_lease = release_owner_pins

    # ------------------------------------------------------------ worker RPC

    def handle_worker_rpc(self, node: Node, w: WorkerHandle, op: str, args):
        self._count_head_rpc(op)
        if op == "submit_task":
            spec = pickle.loads(args[0])
            self.submit_spec(spec)
            return None
        if op == "create_actor":
            unpacked = pickle.loads(args[0])
            self.create_actor(*unpacked)
            return None
        if op == "register_function":
            self.gcs.register_function(args[0], args[1])
            return None
        if op == "get_function":
            return self.gcs.get_function(args[0])
        if op == "get_named_actor":
            info = self.gcs.get_named_actor(args[0], args[1])
            if info is None or info.state == "DEAD":
                return None
            return {"actor_id": info.actor_id, "class_name": info.class_name,
                    "max_task_retries": info.max_task_retries}
        if op == "kill_actor":
            self.kill_actor(args[0], args[1])
            return None
        if op == "actor_location":
            return self.actor_location(args[0])
        if op == "broadcast_object":
            return self.broadcast_object(
                args[0], args[1] if len(args) > 1 else None)
        if op == "pub_publish":
            return self.pubsub.publish(args[0], args[1])
        if op == "pub_poll":
            # round length capped at 2s; the poll runs on a dedicated
            # thread node-side, so parked subscribers can't starve the
            # shared handler pools
            return self.pubsub.poll(args[0], args[1], min(args[2], 2.0),
                                    args[3] if len(args) > 3 else 1000)
        if op == "pub_cursor":
            return self.pubsub.cursor(args[0])
        if op == "cancel_task":
            self.cancel_task(args[0], args[1])
            return None
        if op == "kv":
            sub, rest = args[0], args[1:]
            return getattr(self.gcs, "kv_" + sub)(*rest)
        if op == "stream_next":
            return self.stream_next(args[0], args[1], args[2])
        if op == "state_list":
            return self.state_list(args[0], args[1])
        if op == "object_locations":
            return self.object_locations(args[0])
        if op == "register_owned_object":
            with self._lock:
                self.ref_counts[args[0]] += 1
            return None
        if op == "unregister_owned_object":
            with self._lock:
                self.ref_counts[args[0]] -= 1
                should_delete = self.ref_counts[args[0]] <= 0
            if should_delete and not self._stopped:
                self.delete_object(args[0])
            return None
        if op == "available_resources":
            return self.scheduler.available_resources()
        if op == "cluster_resources":
            return self.scheduler.total_resources()
        if op == "nodes":
            return [
                {"NodeID": n.hex, "Alive": n.alive,
                 "Resources": n.resources_total, "Labels": n.labels}
                for n in self.gcs.nodes.values()
            ]
        if op == "create_placement_group":
            pg = self.scheduler.create_placement_group(args[0], args[1], args[2])
            return pg.pg_id
        if op == "pg_ready":
            pg = self.scheduler.get_placement_group(args[0])
            if pg is None:
                return False
            return pg.ready_event.wait(timeout=args[1])
        if op == "pg_remove":
            self.scheduler.remove_placement_group(args[0])
            return None
        if op == "pg_state":
            pg = self.scheduler.get_placement_group(args[0])
            if pg is None:
                return None
            return {"state": pg.state, "bundles": [b.resources.to_dict() for b in pg.bundles],
                    "bundle_nodes": [b.node_hex for b in pg.bundles]}
        raise ValueError(f"unknown rpc op {op!r}")

    # ------------------------------------------------------------ misc

    def cancel_task(self, oid_or_tid, force: bool = False) -> None:
        tid = oid_or_tid.task_id() if isinstance(oid_or_tid, ObjectID) else oid_or_tid
        with self._lock:
            rec = self.tasks.get(tid)
            if rec is None:
                return
            if rec.state in ("PENDING", "QUEUED", "WAITING_DEPS"):
                rec.cancelled = True
                # state transition happens inside the (settle-guarded)
                # fail path — pre-setting FAILED would trip the guard and
                # skip sealing the cancellation error
                self._fail_task_now(rec, TaskCancelledError("task cancelled"))
                return
            node = self.nodes.get(rec.node_hex) if rec.node_hex else None
            worker_id = rec.worker_id  # set for actor tasks at dispatch
        if rec.state == "RUNNING" and node is not None:
            node.cancel_task(tid, worker_id, force)

    def _record_event(self, spec: TaskSpec, state: str, node_hex=None, error=None):
        from ray_tpu.util.metrics import registry

        registry().record("ray_tpu_tasks_total", "counter",
                          "task state transitions",
                          (("state", state),), 1.0, mode="add")
        self.gcs.record_task_event(TaskEvent(
            task_id=spec.task_id.binary(), name=spec.function_name, state=state,
            node_hex=node_hex, ts=time.time(), attempt=spec.attempt, error=error,
        ))

    def shutdown(self) -> None:
        self._stopped = True
        self._stop_event.set()  # pops every event-paced service loop
        ref_tracker.reset()  # driver-process entries die with the cluster
        from ray_tpu.util import events as events_mod
        from .object_transfer import close_pool

        close_pool()  # pooled transfer connections die with the cluster
        events_mod.flush()
        events_mod.clear_sink(self.record_cluster_events)
        if self._event_writer is not None:
            self._event_writer.close()
        stop_telemetry = getattr(self, "_device_telemetry_stop", None)
        if stop_telemetry is not None:
            stop_telemetry.set()
        self.scheduler.stop()
        if self._node_listener is not None:
            from .protocol import close_listener

            close_listener(self._node_listener)  # wakes parked accept()
            self._node_listener = None
        if self._daemon_pool is not None:
            self._daemon_pool.shutdown(wait=False)
        if getattr(self, "_metrics_server", None) is not None:
            try:
                self._metrics_server.shutdown()
                self._metrics_server.server_close()  # release the socket
            except Exception:
                pass
        with self._lock:
            nodes = list(self.nodes.values())
            self.nodes.clear()
        for node in nodes:
            node.shutdown()
        self.gcs.close()
        # reap the service loops: every one paces on _stop_event or
        # blocks in an accept()/serve_forever the closes above popped
        for t in self._service_threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)


# --------------------------------------------------------------------------- #
# Driver runtime (public API backend in the driver process)
# --------------------------------------------------------------------------- #


class DriverRuntime:
    def __init__(self, head: Head):
        from .direct import DirectActorSubmitter, DirectTaskManager

        self.head = head
        self.job_id = head.job_id
        self._driver_task_id = TaskID.for_driver_task(self.job_id)
        self._put_counter = 0
        self._lock = threading.Lock()
        self._fn_cache: Dict[str, Any] = {}
        # direct (head-bypass) path: the driver owns its eligible plain
        # tasks, submitted straight to the in-process head node. Arg pins
        # are owner-side (the manager's pin table); the head's delete
        # decisions consult them via extra_pin_check and retry deferred
        # deletes when the pin releases at task settle.
        self.direct = DirectTaskManager(
            self._direct_submit,
            ext_wait=lambda oids, t: head.wait_objects(
                list(oids), len(oids), t),
            locate=head.locate_large_object,
            on_unpin=head.release_owner_pins)
        # lost direct results resubmit from this owner's lineage when the
        # head's get loops find no live location
        head.direct_recover = self.direct.recover
        head.extra_pin_check = self.direct.holds_pin
        head.owner_pin_counts = self.direct.pin_counts
        # published driver-owned streams serve remote subscribers straight
        # from the owner table (stream_sub terminates here, not in head
        # records)
        head.owner_stream_next = self.direct.stream_next_remote

        # direct actor calls: ordered caller->actor-node submission; the
        # head only resolves locations and keeps the lifecycle FSM
        self.direct_actors = DirectActorSubmitter(
            self.direct, self._direct_submit, head.actor_location)

    def _direct_submit(self, spec: TaskSpec) -> None:
        self.head.head_node.submit_direct(
            spec, ("driver", self.direct.complete,
                   self.direct.on_stream_item))

    @property
    def mode(self) -> str:
        return "DRIVER"

    def is_initialized(self) -> bool:
        return True

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    # ---- objects ----
    def put(self, value: Any, _owner=None) -> ObjectRef:
        with self._lock:
            self._put_counter += 1
            idx = self._put_counter
        oid = ObjectID.for_put(self._driver_task_id, idx)
        sobj = serialization.serialize(value)
        node = self.head.head_node
        cfg = global_config()
        if sobj.total_bytes <= cfg.max_direct_call_object_size:
            node.store.put_inline(oid, sobj.to_bytes(), False)
        else:
            _, view = node.store.create(oid, sobj.total_bytes)
            # writev-style: source buffers pack straight into the arena
            sobj.write_into_view(view)
            node.store.seal(oid, False)
        self.head.on_object_sealed(oid, node.hex)
        # registered ref: +1 now, -1 when the ObjectRef is GC'd -> deletable
        ref = ObjectRef(oid)
        ref_tracker.annotate(oid, ref_tracker.KIND_PUT,
                             size=sobj.total_bytes, creator="driver")
        return ref

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            local = self.direct.get_local(r.id, remaining)
            if local is not None and local[0] is not None:
                payload, is_error = local
            else:
                payload, is_error = self.head.get_object_payload(r.id, remaining)
            value = serialization.deserialize(payload)
            if is_error:
                raise value
            out.append(value)
        return out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        """Mixed wait over direct-owned results (in-process) and cluster
        objects. Event-driven: direct completions and head seals both set
        the waiter event — no fixed-period polling. ``fetch_local`` is
        honored: remote-only objects only count as ready once their pull
        (triggered by this wait) lands locally."""
        oids = [r.id for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        if fetch_local:
            # completed direct-owned results count as ready immediately
            # (their get() resolves from the owner table), but the bytes
            # may still sit on the producer node. num_returns=0 returns
            # after the pull-spawning pass, so this wait still starts
            # the transfer — the side effect windowed iterator prefetch
            # (data/iterator.py) relies on for direct-path task results.
            settled = [o for o in self.direct.ready_subset(oids)
                       if self.direct.result_node(o) is not None]
            if settled:
                self.head.wait_objects(settled, 0, 0.0, fetch_local=True)
        ev = threading.Event()
        self.direct.add_waiter(ev)
        self.head.add_seal_waiter(ev)
        try:
            while True:
                ready_ids = set(self.direct.ready_subset(oids))
                pending = self.direct.pending_oids(oids)
                rest = [o for o in oids if o not in ready_ids
                        and o not in pending]
                if rest and len(ready_ids) < num_returns:
                    ready_ids |= set(self.head.wait_objects(
                        rest, num_returns - len(ready_ids), 0.0,
                        fetch_local=fetch_local))
                if len(ready_ids) >= num_returns:
                    break
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                ev.wait(0.5 if remaining is None else min(0.5, remaining))
                ev.clear()
        finally:
            self.direct.remove_waiter(ev)
            self.head.remove_seal_waiter(ev)
        ready = [r for r in refs if r.id in ready_ids][:num_returns]
        ready_set = {r.id for r in ready}
        not_ready = [r for r in refs if r.id not in ready_set]
        return ready, not_ready

    def object_locations(self, oids: List[ObjectID]) -> List[List[str]]:
        """Per-object holder node hexes; direct-owned results the head
        hasn't learned about yet resolve from the owner's table."""
        out = self.head.object_locations(oids)
        self.direct.fill_result_locations(oids, out)
        return out

    # ---- tasks ----
    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        from .direct import direct_eligible

        if global_config().direct_task_enabled and direct_eligible(spec):
            ready = self.direct.register(spec)
            if ready is not None:  # else: dep resolver submits it later
                self._direct_submit(ready)
        else:
            self.head.submit_spec(spec)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        ref_tracker.annotate_many(
            spec.return_ids(),
            ref_tracker.KIND_ACTOR_RETURN if spec.actor_id is not None
            else ref_tracker.KIND_TASK_RETURN,
            creator=spec.function_name)
        return refs

    def register_function(self, function_id: str, payload: bytes) -> None:
        self.head.gcs.register_function(function_id, payload)

    def get_function(self, function_id: str):
        if function_id not in self._fn_cache:
            payload = self.head.gcs.get_function(function_id)
            if payload is None:
                raise RuntimeError(f"function {function_id} not registered")
            self._fn_cache[function_id] = pickle.loads(payload)
        return self._fn_cache[function_id]

    def create_actor_record(self, spec, name, namespace, max_restarts,
                            detached, max_task_retries=0):
        self.head.create_actor(spec, name, namespace, max_restarts, detached,
                               max_task_retries)

    def get_actor_info(self, name: str, namespace: str):
        info = self.head.gcs.get_named_actor(name, namespace)
        if info is None or info.state == "DEAD":
            return None
        return {"actor_id": info.actor_id, "class_name": info.class_name,
                "max_task_retries": info.max_task_retries}

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.head.kill_actor(actor_id, no_restart)

    def cancel_task(self, oid: ObjectID, force: bool = False):
        if self.direct.cancel(oid):
            # owner-side mark + node-side dequeue/interrupt
            self.head.head_node.cancel_direct(oid.task_id(), force)
            return
        self.head.cancel_task(oid, force)

    def kv(self, op: str, *args):
        return getattr(self.head.gcs, "kv_" + op)(*args)

    def stream_next(self, task_id, index: int, timeout=None, owner=None):
        # owner-side stream buffer first (direct-path streams this driver
        # owns); borrowed handles with an owner route subscribe to the
        # OWNER via the head node's peer mesh; only head-path streams
        # fall through to the head's stream records
        rep = self.direct.stream_next(task_id, index, timeout)
        if rep is not None:
            return rep
        if owner is not None:
            from .direct import bounded_sub_rounds

            return bounded_sub_rounds(
                lambda t: self.head.head_node.serve_stream_sub(
                    owner, task_id, index, t), timeout)
        return self.head.stream_next(task_id, index, timeout)

    def stream_owner_route(self):
        """This driver's stream-owner address: subscriptions terminate at
        the driver's direct manager (head.owner_stream_next hook)."""
        return ("d", self.head.head_node.hex)

    def publish_stream(self, task_id) -> bool:
        # generator handle serialized out of this process (object_ref):
        # True = this driver owns it and will serve subscribers
        return self.direct.publish_stream(task_id)

    # ---- refs ----
    def add_local_ref(self, oid: ObjectID) -> None:
        ref_tracker.incref(oid)
        with self.head._lock:
            self.head.ref_counts[oid] += 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        ref_tracker.decref(oid)
        self.direct.drop(oid)
        with self.head._lock:
            self.head.ref_counts[oid] -= 1
            should_delete = self.head.ref_counts[oid] <= 0
        if should_delete and not self.head._stopped:
            self.head.delete_object(oid)

    def add_borrow_ref(self, oid: ObjectID) -> None:
        with self.head._lock:
            self.head.ref_counts[oid] += 1

    # ---- cluster info ----
    def runtime_context(self) -> dict:
        return {
            "job_id": self.job_id,
            "node_id": self.head.head_node.hex,
            "worker_id": b"driver",
            "task_id": self._driver_task_id,
            "actor_id": None,
            "accelerator_ids": {},
            "mode": "DRIVER",
        }

    def available_resources(self):
        return self.head.scheduler.available_resources()

    def cluster_resources(self):
        return self.head.scheduler.total_resources()

    def nodes(self):
        return [
            {"NodeID": n.hex, "Alive": n.alive, "Resources": n.resources_total,
             "Labels": n.labels}
            for n in self.head.gcs.nodes.values()
        ]

    def actor_method_call(self, spec: TaskSpec) -> List[ObjectRef]:
        cfg = global_config()
        if (cfg.direct_task_enabled and cfg.direct_actor_enabled
                and self.direct_actors.try_submit(spec)):
            refs = [ObjectRef(oid) for oid in spec.return_ids()]
            ref_tracker.annotate_many(spec.return_ids(),
                                      ref_tracker.KIND_ACTOR_RETURN,
                                      creator=spec.function_name)
            return refs
        # direct path disabled by config (a whole-session toggle, so
        # every call to every actor takes the same path and per-caller
        # ordering is structural): head path
        return self.submit_task(spec)

    def create_placement_group(self, bundles, strategy, name=""):
        pg = self.head.scheduler.create_placement_group(bundles, strategy, name)
        return pg.pg_id

    def placement_group_op(self, op: str, *args):
        return self.head.handle_worker_rpc(None, None, "pg_" + op, args)


_current_runtime = None


def set_current_runtime(rt) -> None:
    global _current_runtime
    _current_runtime = rt


def get_current_runtime():
    return _current_runtime
