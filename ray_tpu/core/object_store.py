"""Node-local object store: shared-memory arena + in-process memory store.

Analog of the reference's plasma store (``src/ray/object_manager/plasma/``) and
the CoreWorker in-process memory store (``store_provider/memory_store/``):

- Small objects (< ``max_direct_call_object_size``) live inline in the owner's
  memory store and travel inside RPC replies (reference: ray_config_def.h:199).
- Large objects are written into a node-wide mmap'd arena on /dev/shm so every
  worker process on the node reads them zero-copy (reference: plasma fd-passing
  via fling.cc; here all workers map the same session file).
- Allocation uses the native C++ allocator (``ray_tpu._native.plasma``) when
  built, else a Python first-fit free list (reference: dlmalloc arena).
- When the arena fills, sealed objects are spilled to disk files and restored
  on demand (reference: local_object_manager.h SpillObjects / fallback
  allocation plasma_allocator.h:83-97).
"""

from __future__ import annotations

import contextlib
import mmap
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .config import global_config
from .exceptions import ObjectStoreFullError, ObjectLostError
from .ids import ObjectID
# Store write traffic. The data-pipeline benches assert operator fusion
# reduces per-stage materialization through these (puts = inline + arena
# creations, bytes = payload bytes written). Imported lazily: this module
# loads inside the ray_tpu.core import chain, before ray_tpu.util's
# package __init__ (which needs ray_tpu.remote) can run. Both counters
# publish via ONE atomic global assignment — concurrent first puts must
# never observe a half-initialized pair.
_m_store_put = None


def _count_put(nbytes: int) -> None:
    global _m_store_put
    m = _m_store_put
    if m is None:
        from ray_tpu.util.metrics import Counter

        m = (Counter("ray_tpu_object_store_puts_total",
                     "Objects written into a local store"),
             Counter("ray_tpu_object_store_put_bytes_total",
                     "Bytes written into local stores"))
        _m_store_put = m
    m[0].inc()
    m[1].inc(nbytes)


# Store-pressure telemetry (the `ray memory` store half): lazily-created
# counter bundle shared by every store in the process, tagged {node}.
# Same one-shot atomic publish discipline as _m_store_put above.
_m_store_tel = None


def _telemetry():
    global _m_store_tel
    m = _m_store_tel
    if m is None:
        from ray_tpu.util.metrics import Counter, Gauge

        m = {
            "spilled": Counter("ray_tpu_object_store_spilled_objects_total",
                               "Objects spilled to disk under pressure"),
            "spilled_bytes": Counter(
                "ray_tpu_object_store_spilled_bytes_total",
                "Bytes spilled to disk under pressure"),
            "restored": Counter(
                "ray_tpu_object_store_restored_objects_total",
                "Spilled objects restored into the arena"),
            "restored_bytes": Counter(
                "ray_tpu_object_store_restored_bytes_total",
                "Bytes restored from spill files"),
            "evicted": Counter("ray_tpu_object_store_evicted_objects_total",
                               "Unreferenced objects evicted under pressure"),
            "evicted_bytes": Counter(
                "ray_tpu_object_store_evicted_bytes_total",
                "Bytes evicted under pressure"),
            "used": Gauge("ray_tpu_object_store_bytes_used",
                          "Arena bytes allocated"),
            "free": Gauge("ray_tpu_object_store_bytes_free",
                          "Arena bytes free"),
            "inline": Gauge("ray_tpu_object_store_inline_bytes",
                            "Bytes held inline in the memory store"),
            "frag": Gauge("ray_tpu_object_store_fragmentation_ratio",
                          "1 - largest free extent / total free bytes"),
        }
        _m_store_tel = m
    return m


# --------------------------------------------------------------------------- #
# Allocator
# --------------------------------------------------------------------------- #


class FreeListAllocator:
    """First-fit free-list allocator over a fixed arena (Python fallback)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        # sorted list of (offset, size) free extents
        self._free: List[Tuple[int, int]] = [(0, capacity)]
        self._allocated: Dict[int, int] = {}
        self._lock = threading.Lock()

    def allocate(self, size: int) -> Optional[int]:
        size = max(8, (size + 63) & ~63)  # 64B alignment
        with self._lock:
            for i, (off, sz) in enumerate(self._free):
                if sz >= size:
                    if sz == size:
                        self._free.pop(i)
                    else:
                        self._free[i] = (off + size, sz - size)
                    self._allocated[off] = size
                    return off
        return None

    def free(self, offset: int) -> None:
        with self._lock:
            size = self._allocated.pop(offset)
            self._free.append((offset, size))
            self._free.sort()
            # coalesce
            merged: List[Tuple[int, int]] = []
            for off, sz in self._free:
                if merged and merged[-1][0] + merged[-1][1] == off:
                    merged[-1] = (merged[-1][0], merged[-1][1] + sz)
                else:
                    merged.append((off, sz))
            self._free = merged

    def bytes_allocated(self) -> int:
        with self._lock:
            return sum(self._allocated.values())

    def free_stats(self) -> Tuple[int, int, int]:
        """(free_bytes, free_extents, largest_free_extent) — the
        fragmentation signal behind the store gauges."""
        with self._lock:
            if not self._free:
                return (0, 0, 0)
            sizes = [sz for _off, sz in self._free]
            return (sum(sizes), len(sizes), max(sizes))


def _make_allocator(capacity: int):
    try:
        from ray_tpu._native.plasma import NativeAllocator

        return NativeAllocator(capacity)
    except Exception:
        return FreeListAllocator(capacity)


# --------------------------------------------------------------------------- #
# Arena (one per node, mapped by every worker on that node)
# --------------------------------------------------------------------------- #


class PlasmaArena:
    """A single mmap'd file on /dev/shm holding all large-object payloads."""

    def __init__(self, path: str, capacity: int, create: bool):
        self.path = path
        self.capacity = capacity
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self._fd = os.open(path, flags, 0o600)
        if create:
            os.ftruncate(self._fd, capacity)
        self._mm = mmap.mmap(self._fd, capacity)
        self.allocator = _make_allocator(capacity) if create else None

    @property
    def fd(self) -> int:
        """Backing-file descriptor (os.sendfile source for zero-copy sends)."""
        return self._fd

    def view(self, offset: int, size: int) -> memoryview:
        return memoryview(self._mm)[offset : offset + size]

    def close(self, unlink: bool = False):
        # Zero-copy readers may still hold memoryviews into the map; in that
        # case leave the mapping to the GC and just unlink the backing file.
        try:
            self._mm.close()
        except BufferError:
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


# --------------------------------------------------------------------------- #
# Store
# --------------------------------------------------------------------------- #


class ReadHandle:
    """Pinned view of a sealed arena extent (see open_read): ``view`` for
    mmap reads, (``fd``, ``offset``) for os.sendfile zero-copy sends."""

    __slots__ = ("view", "fd", "offset")

    def __init__(self, view: memoryview, fd: int, offset: int):
        self.view = view
        self.fd = fd
        self.offset = offset


@dataclass
class ObjectEntry:
    object_id: ObjectID
    size: int = 0
    inline: Optional[bytes] = None  # small objects
    offset: int = -1  # arena offset for large objects
    sealed: bool = False
    is_error: bool = False  # payload is a serialized exception
    mapped: bool = False  # a zero-copy view was handed out; do not move
    spilled_path: Optional[str] = None
    owner_node: Optional[bytes] = None
    ref_count: int = 0
    last_access: float = field(default_factory=time.monotonic)
    creating: bool = False  # allocated, being written
    # transfer readers streaming this extent to a peer (open_read): the
    # extent must not move or free mid-send; unlike ``mapped`` the pin is
    # scoped — delete() during a send defers the free to the last release
    readers: int = 0
    pending_free: bool = False  # deleted while readers > 0
    created_ts: float = field(default_factory=time.time)  # wall-clock age
    # receive-side replica (node-to-node pull/push/subscription cache):
    # the bytes exist elsewhere, so eviction never destroys the only copy
    transfer: bool = False


class LocalObjectStore:
    """Node-local store combining inline memory store + shared arena.

    Thread-safe; the node's RPC threads and driver call into it concurrently.
    """

    def __init__(self, session_dir: str, node_hex: str, capacity: Optional[int] = None,
                 pin_check=None, pin_check_authoritative: bool = True):
        # pin_check(oid) -> bool: owner-side liveness (head ref counts). Read
        # lock-free by design: called under the store lock, and the head may
        # call into the store while holding its own lock (ABBA otherwise).
        # pin_check_authoritative=False (daemon stores, which only see the
        # node-local holder lease — the old per-object is_pinned head RPC
        # is gone): eviction is then restricted to TRANSFER copies; primary
        # copies spill to disk instead of being destroyed, since a remote
        # owner may still reference them.
        self._pin_check = pin_check or (lambda oid: False)
        self._pin_authoritative = pin_check_authoritative
        cfg = global_config()
        self.capacity = capacity or cfg.object_store_memory
        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else session_dir
        self.arena_path = os.path.join(shm_dir, f"raytpu_plasma_{node_hex}")
        self.arena = PlasmaArena(self.arena_path, self.capacity, create=True)
        self.spill_dir = cfg.object_spilling_dir or os.path.join(session_dir, "spill")
        os.makedirs(self.spill_dir, exist_ok=True)
        self._entries: Dict[ObjectID, ObjectEntry] = {}
        from .lock_debug import tracked_rlock

        self._lock = tracked_rlock("LocalObjectStore._lock")
        self._sealed_cv = threading.Condition(self._lock)
        # telemetry state: one tag set per node, gauges rate-limited (the
        # put hot path must not pay a registry write per call)
        self._tag_key = (("node", node_hex[:12]),)
        self._inline_bytes = 0
        self._counters = {"spilled": 0, "spilled_bytes": 0, "restored": 0,
                          "restored_bytes": 0, "evicted": 0,
                          "evicted_bytes": 0}
        self._gauges_last = 0.0
        # high-watermark edge detector (+ periodic re-emit while above)
        self._above_watermark = False
        self._watermark_last_emit = 0.0

    # -- telemetry ---------------------------------------------------------

    def _publish_gauges(self, force: bool = False) -> None:
        """Refresh the store gauges, at most every 0.5 s (mutation sites
        call this opportunistically; the metrics sampler reads gauges on
        its own cadence, so sub-second staleness is invisible)."""
        now = time.monotonic()
        if not force and now - self._gauges_last < 0.5:
            return
        self._gauges_last = now
        try:
            m = _telemetry()
            alloc = self.arena.allocator
            used = alloc.bytes_allocated() if alloc is not None else 0
            tk = self._tag_key
            m["used"].set(float(used), tag_key=tk)
            m["free"].set(float(self.capacity - used), tag_key=tk)
            m["inline"].set(float(self._inline_bytes), tag_key=tk)
            free_stats = getattr(alloc, "free_stats", None)
            if free_stats is not None:
                total, _n, largest = free_stats()
                frag = (1.0 - largest / total) if total else 0.0
                m["frag"].set(frag, tag_key=tk)
        except Exception:
            pass  # metrics must never break the store

    def _count(self, key: str, n: int, nbytes: int) -> None:
        """Record a spill/restore/evict increment (rare path)."""
        with self._lock:  # RLock: callers may already hold it
            self._counters[key] += n
            self._counters[key + "_bytes"] += nbytes
        try:
            m = _telemetry()
            m[key].inc(float(n), tag_key=self._tag_key)
            m[key + "_bytes"].inc(float(nbytes), tag_key=self._tag_key)
        except Exception:
            pass

    def _check_watermark(self) -> None:
        """Emit a WARNING cluster event when arena usage crosses the high
        watermark, naming the top consumers by creation callsite (the
        owner-side ref tracker knows who minted each object). Edge-
        triggered, with a 30 s re-emit while the store stays above."""
        cfg = global_config()
        wm = cfg.object_store_high_watermark
        if wm <= 0 or self.arena.allocator is None:
            return
        used = self.arena.allocator.bytes_allocated()
        above = used >= wm * self.capacity
        now = time.monotonic()
        if not above:
            self._above_watermark = False
            return
        if self._above_watermark and now - self._watermark_last_emit < 30.0:
            return
        self._above_watermark = True
        self._watermark_last_emit = now
        with self._lock:
            top = sorted((e for e in self._entries.values()
                          if e.offset >= 0 and e.spilled_path is None),
                         key=lambda e: -e.size)[:5]
            top = [(e.object_id, e.size) for e in top]
        from ray_tpu.core import ref_tracker
        from ray_tpu.util import events as events_mod

        consumers = []
        for oid, size in top:
            info = ref_tracker.lookup(oid)
            consumers.append({
                "object_id": oid.hex(), "bytes": size,
                "callsite": (info[3] if info and info[3] else "<unknown>"),
                "kind": (info[1] if info else None),
            })
        names = ", ".join(f"{c['callsite']}={c['bytes']}B"
                          for c in consumers) or "none"
        events_mod.emit(
            "WARNING", events_mod.SOURCE_OBJECT_STORE,
            f"object store at {100.0 * used / self.capacity:.0f}% of "
            f"capacity ({used}/{self.capacity} bytes); top consumers: "
            f"{names}", entity_id=self.arena_path,
            used=used, capacity=self.capacity, watermark=wm,
            top_consumers=consumers)

    # -- creation ----------------------------------------------------------

    def put_inline(self, oid: ObjectID, payload: bytes, is_error: bool = False,
                   transfer: bool = False):
        with self._lock:
            e = self._entries.get(oid)
            if e is not None and e.sealed:
                return  # idempotent re-put (retries)
            if e is not None and e.inline is not None:
                self._inline_bytes -= len(e.inline)
            self._entries[oid] = ObjectEntry(
                oid, size=len(payload), inline=bytes(payload), sealed=True,
                is_error=is_error, transfer=transfer,
            )
            self._inline_bytes += len(payload)
            self._sealed_cv.notify_all()
        if not transfer:
            _count_put(len(payload))
        self._publish_gauges()

    def create(self, oid: ObjectID, size: int,
               transfer: bool = False) -> Tuple[int, memoryview]:
        """Allocate arena space; returns (offset, writable view). Spills/evicts
        under pressure (reference: create_request_queue.cc backpressure).

        ``transfer=True`` marks a receive-side allocation (node-to-node
        pull/push of bytes that already exist elsewhere): those are not
        counted as puts, so the put counters measure object
        MATERIALIZATIONS, not replication traffic (which has its own
        metrics in object_transfer)."""
        cfg = global_config()
        deadline = time.monotonic() + 30.0
        while True:
            off = self.arena.allocator.allocate(size)
            if off is not None:
                break
            if not self._reclaim(size):
                if time.monotonic() > deadline:
                    raise ObjectStoreFullError(
                        f"object store full: need {size} bytes "
                        f"(capacity {self.capacity})"
                    )
                time.sleep(cfg.object_store_full_delay_ms / 1000.0)
        with self._lock:
            stale = self._entries.get(oid)
            if stale is not None and stale.offset >= 0 and stale.spilled_path is None:
                if stale.readers > 0:  # open_read sender mid-stream
                    stale.pending_free = True
                else:
                    self.arena.allocator.free(stale.offset)  # retry overwrote entry
            self._entries[oid] = ObjectEntry(oid, size=size, offset=off,
                                             creating=True, transfer=transfer)
        if not transfer:
            _count_put(size)
        self._publish_gauges()
        self._check_watermark()
        return off, self.arena.view(off, size)

    def seal(self, oid: ObjectID, is_error: bool = False):
        with self._lock:
            e = self._entries[oid]
            e.sealed = True
            e.creating = False
            e.is_error = is_error
            self._sealed_cv.notify_all()

    # -- reads -------------------------------------------------------------

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
            return e is not None and e.sealed

    def wait_sealed(self, oid: ObjectID, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._sealed_cv:
            while True:
                e = self._entries.get(oid)
                if e is not None and e.sealed:
                    return True
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._sealed_cv.wait(remaining if remaining is not None else 1.0)

    def get_payload(self, oid: ObjectID) -> Tuple[object, bool]:
        """Returns (buffer, is_error). Buffer is bytes (inline) or a zero-copy
        memoryview into the arena; restores from spill if needed."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                raise ObjectLostError(oid, f"object {oid.hex()} not in local store")
            e.last_access = time.monotonic()
            if e.inline is not None:
                return e.inline, e.is_error
            if e.spilled_path is not None:
                self._restore_locked(e)
            e.mapped = True
            return self.arena.view(e.offset, e.size), e.is_error

    def read_meta(self, oid: ObjectID) -> Optional[Tuple[int, bool]]:
        """(size, is_error) for a sealed object, else None. Does not pin."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                return None
            return e.size, e.is_error

    def read_chunk(self, oid: ObjectID, start: int, n: int) -> Optional[bytes]:
        """Copy out payload[start:start+n] for node-to-node transfer.

        Re-looks-up the entry per call so a transfer never pins the object:
        returns None if it was deleted/evicted mid-stream (puller retries
        with a fresh location). Serves spilled objects straight from disk.
        """
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                return None
            e.last_access = time.monotonic()
            if e.inline is not None:
                return e.inline[start:start + n]
            if e.spilled_path is not None:
                try:
                    with open(e.spilled_path, "rb") as f:
                        f.seek(start)
                        return f.read(n)
                except OSError:
                    return None
            return bytes(self.arena.view(e.offset, e.size)[start:start + n])

    @contextlib.contextmanager
    def open_read(self, oid: ObjectID):
        """Zero-copy transfer read: yields a ``ReadHandle`` over the sealed
        arena extent, pinned against move/free for the duration (the
        node-to-node sender streams the payload straight out of the mmap —
        or via ``os.sendfile`` from the backing tmpfs fd). Yields None for
        inline/spilled/absent entries — caller falls back to the copying
        ``read_chunk`` path. A concurrent delete() defers the extent free
        to the last reader's release instead of yanking memory out from
        under an in-flight send."""
        with self._lock:
            e = self._entries.get(oid)
            if (e is None or not e.sealed or e.inline is not None
                    or e.spilled_path is not None or e.offset < 0):
                e = None
            else:
                e.readers += 1
                e.last_access = time.monotonic()
                handle = ReadHandle(self.arena.view(e.offset, e.size),
                                    self.arena.fd, e.offset)
        try:
            yield handle if e is not None else None
        finally:
            if e is not None:
                with self._lock:
                    e.readers -= 1
                    if (e.readers <= 0 and e.pending_free
                            and not e.mapped and e.offset >= 0):
                        self.arena.allocator.free(e.offset)
                        e.pending_free = False
                        e.offset = -1

    def entry_info(self, oid: ObjectID) -> Optional[Tuple[int, int, bool]]:
        """(offset, size, is_error) for sealed arena objects, for direct worker
        mmap reads; None if inline/absent/spilled."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed or e.inline is not None:
                return None
            if e.spilled_path is not None:
                self._restore_locked(e)
            e.last_access = time.monotonic()
            e.mapped = True
            return e.offset, e.size, e.is_error

    # -- lifetime ----------------------------------------------------------

    def add_ref(self, oid: ObjectID, n: int = 1):
        with self._lock:
            e = self._entries.get(oid)
            if e is not None:
                e.ref_count += n

    def remove_ref(self, oid: ObjectID, n: int = 1):
        with self._lock:
            e = self._entries.get(oid)
            if e is not None:
                e.ref_count = max(0, e.ref_count - n)

    def delete(self, oid: ObjectID):
        with self._lock:
            e = self._entries.pop(oid, None)
            if e is None:
                return
            if e.inline is not None:
                self._inline_bytes -= len(e.inline)
            # plasma lifetime contract: an extent whose zero-copy view was
            # handed out (mapped) is NEVER returned to the allocator — a
            # reader's array may still alias it, and reuse would silently
            # corrupt what it sees. The extent leaks until store close
            # (the reference frees plasma buffers only when all client
            # references release; we track at entry granularity).
            if e.offset >= 0 and e.spilled_path is None and not e.mapped:
                if e.readers > 0:
                    # an open_read sender is mid-stream over this extent:
                    # the last release frees it (see open_read)
                    e.pending_free = True
                else:
                    self.arena.allocator.free(e.offset)
            if e.spilled_path:
                try:
                    os.unlink(e.spilled_path)
                except OSError:
                    pass
        self._publish_gauges()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            spilled = [e for e in self._entries.values() if e.spilled_path]
            out = {
                "num_objects": len(self._entries),
                "bytes_allocated": self.arena.allocator.bytes_allocated(),
                "capacity": self.capacity,
                "num_spilled": len(spilled),
                "bytes_inline": self._inline_bytes,
                "bytes_spilled": sum(e.size for e in spilled),
            }
            out.update(self._counters)
        return out

    def object_infos(self) -> List[Tuple[ObjectID, int, bool, bool, float,
                                         int]]:
        """Per-object store dump for the cluster memory table
        (``Head.memory_table``): (oid, size, inline?, spilled?,
        created_ts, store_ref_count) for every sealed entry."""
        with self._lock:
            return [(e.object_id, e.size, e.inline is not None,
                     e.spilled_path is not None, e.created_ts, e.ref_count)
                    for e in self._entries.values() if e.sealed]

    # -- spilling / eviction ----------------------------------------------

    def _reclaim(self, need: int) -> bool:
        """Evict unreferenced sealed objects (LRU), then spill referenced ones."""
        cfg = global_config()
        evicted = evicted_bytes = spilled = spilled_bytes = 0
        with self._lock:
            candidates = sorted(
                (e for e in self._entries.values()
                 if e.sealed and e.offset >= 0 and e.spilled_path is None),
                key=lambda e: e.last_access,
            )
            freed = 0
            for e in candidates:
                if freed >= need:
                    break
                # never relocate/free an entry whose zero-copy view was handed
                # out (a reader may alias the arena range); explicit delete()
                # via refcount-0 is the user-driven path that still frees it
                if (e.ref_count <= 0 and not e.mapped and e.readers <= 0
                        and not self._pin_check(e.object_id)
                        and (self._pin_authoritative or e.transfer)):
                    self.arena.allocator.free(e.offset)
                    del self._entries[e.object_id]
                    freed += e.size
                    evicted += 1
                    evicted_bytes += e.size
            if freed < need and cfg.object_spilling_enabled:
                for e in candidates:
                    if freed >= need:
                        break
                    if (e.object_id not in self._entries or e.mapped
                            or e.readers > 0):
                        # never move an object a zero-copy reader may alias
                        continue
                    self._spill_locked(e)
                    freed += e.size
                    spilled += 1
                    spilled_bytes += e.size
            ok = freed > 0 or freed >= need
        if evicted:
            self._count("evicted", evicted, evicted_bytes)
        if spilled:
            self._count("spilled", spilled, spilled_bytes)
        self._publish_gauges(force=True)
        self._emit_pressure_events(evicted, evicted_bytes, spilled,
                                   spilled_bytes)
        return ok

    def _emit_pressure_events(self, evicted: int, evicted_bytes: int,
                              spilled: int, spilled_bytes: int) -> None:
        """Memory-pressure cluster events, emitted outside the store lock
        (reference: the 'object store is spilling' autoscaler warning).
        Rate-limited to one emit per second with counts aggregated in
        between — _reclaim sits on the allocation retry path, and a
        pressure wave must not turn into an event flood of blocking
        sends (same policy as node._emit_spillback)."""
        if not evicted and not spilled:
            return
        acc = getattr(self, "_pressure_acc", None)
        if acc is None:
            acc = self._pressure_acc = [0, 0, 0, 0]
            self._pressure_last_emit = 0.0
        acc[0] += evicted
        acc[1] += evicted_bytes
        acc[2] += spilled
        acc[3] += spilled_bytes
        now = time.monotonic()
        if now - self._pressure_last_emit < 1.0:
            return
        self._pressure_last_emit = now
        evicted, evicted_bytes, spilled, spilled_bytes = acc
        self._pressure_acc = [0, 0, 0, 0]
        from ray_tpu.util import events as events_mod

        if evicted:
            events_mod.emit(
                "INFO", events_mod.SOURCE_OBJECT_STORE,
                f"evicted {evicted} object(s) ({evicted_bytes} bytes) "
                f"under memory pressure", entity_id=self.arena_path,
                count=evicted, bytes=evicted_bytes)
        if spilled:
            events_mod.emit(
                "WARNING", events_mod.SOURCE_OBJECT_STORE,
                f"spilled {spilled} object(s) ({spilled_bytes} bytes) "
                f"to {self.spill_dir}", entity_id=self.arena_path,
                count=spilled, bytes=spilled_bytes)

    def _spill_locked(self, e: ObjectEntry):
        path = os.path.join(self.spill_dir, e.object_id.hex())
        with open(path, "wb") as f:
            f.write(self.arena.view(e.offset, e.size))
        self.arena.allocator.free(e.offset)
        e.spilled_path = path
        e.offset = -1

    def _restore_locked(self, e: ObjectEntry):
        off = self.arena.allocator.allocate(e.size)
        if off is None:
            self._reclaim(e.size)
            off = self.arena.allocator.allocate(e.size)
            if off is None:
                raise ObjectStoreFullError("cannot restore spilled object")
        with open(e.spilled_path, "rb") as f:
            data = f.read()
        self.arena.view(off, e.size)[:] = data
        try:
            os.unlink(e.spilled_path)
        except OSError:
            pass
        e.spilled_path = None
        e.offset = off
        self._count("restored", 1, e.size)
        # a restore allocates like a create does — a read-heavy workload
        # can cross the watermark with no create() in sight
        self._check_watermark()

    def close(self):
        self.arena.close(unlink=True)


class ArenaClient:
    """Worker-side read/write mapping of a node's arena (plasma client analog)."""

    def __init__(self, arena_path: str, capacity: int):
        self.arena = PlasmaArena(arena_path, capacity, create=False)

    def view(self, offset: int, size: int) -> memoryview:
        return self.arena.view(offset, size)

    def close(self):
        self.arena.close(unlink=False)
