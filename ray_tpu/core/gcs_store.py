"""Pluggable GCS table storage: the fault-tolerance seam.

Reference: the GCS's StoreClient abstraction —
``InMemoryStoreClient`` (src/ray/gcs/store_client/in_memory_store_client.h:31,
default, state dies with the process) vs ``RedisStoreClient``
(redis_store_client.h:33, enables GCS restart recovery). Same split here:
:class:`InMemoryStore` is a no-op sink; :class:`FileStore` journals every
durable-table write (KV, function registry, job history, workflow-style
metadata) to an append-only log with periodic snapshot compaction, and a
restarted head (``ray_tpu.init(storage=...)``) replays it.

Redis isn't in this environment (and a TPU-pod head has a local disk /
NFS mount), so the durable backend is a file journal — same recovery
contract, zero extra services.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Iterator, Optional, Tuple


class GcsStore:
    """put/delete land synchronously; load() replays at construction."""

    def put(self, table: str, key: Any, value: Any) -> None:
        raise NotImplementedError

    def delete(self, table: str, key: Any) -> None:
        raise NotImplementedError

    def load(self) -> Dict[str, Dict[Any, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStore(GcsStore):
    def put(self, table: str, key: Any, value: Any) -> None:
        pass

    def delete(self, table: str, key: Any) -> None:
        pass

    def load(self) -> Dict[str, Dict[Any, Any]]:
        return {}


class FileStore(GcsStore):
    """Append-only journal + snapshot under a directory.

    Layout: ``snapshot.pkl`` (full table dump) + ``journal.pkl`` (stream of
    pickled ("put"|"del", table, key, value) records since the snapshot).
    Writes append+flush; after ``compact_every`` journal records the state
    is re-snapshotted and the journal truncated.
    """

    def __init__(self, path: str, compact_every: int = 1000):
        self.dir = path
        os.makedirs(path, exist_ok=True)
        self._snap_path = os.path.join(path, "snapshot.pkl")
        self._journal_path = os.path.join(path, "journal.pkl")
        self._compact_every = compact_every
        self._lock = threading.Lock()
        self._tables = self._replay()
        self._journal = open(self._journal_path, "ab")
        self._since_compact = 0

    def _replay(self) -> Dict[str, Dict[Any, Any]]:
        tables: Dict[str, Dict[Any, Any]] = {}
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    tables = pickle.load(f)
            except Exception:
                tables = {}
        if os.path.exists(self._journal_path):
            try:
                with open(self._journal_path, "rb") as f:
                    while True:
                        try:
                            op, table, key, value = pickle.load(f)
                        except EOFError:
                            break
                        t = tables.setdefault(table, {})
                        if op == "put":
                            t[key] = value
                        else:
                            t.pop(key, None)
            except Exception:
                pass  # torn tail record: keep what replayed cleanly
        return tables

    def _append(self, record: Tuple) -> None:
        pickle.dump(record, self._journal)
        self._journal.flush()
        self._since_compact += 1
        if self._since_compact >= self._compact_every:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._tables, f)
        os.replace(tmp, self._snap_path)
        self._journal.close()
        self._journal = open(self._journal_path, "wb")
        self._since_compact = 0

    def put(self, table: str, key: Any, value: Any) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            self._append(("put", table, key, value))

    def delete(self, table: str, key: Any) -> None:
        with self._lock:
            self._tables.setdefault(table, {}).pop(key, None)
            self._append(("del", table, key, None))

    def load(self) -> Dict[str, Dict[Any, Any]]:
        with self._lock:
            return {t: dict(kv) for t, kv in self._tables.items()}

    def close(self) -> None:
        with self._lock:
            try:
                self._journal.close()
            except Exception:
                pass
