"""Pluggable GCS table storage: the fault-tolerance seam.

Reference: the GCS's StoreClient abstraction —
``InMemoryStoreClient`` (src/ray/gcs/store_client/in_memory_store_client.h:31,
default, state dies with the process) vs ``RedisStoreClient``
(redis_store_client.h:33, enables GCS restart recovery). Same split here:
:class:`InMemoryStore` is a no-op sink; :class:`FileStore` journals every
durable-table write (KV, function registry, actor/placement records, the
object directory, job history) to an append-only log with periodic
snapshot compaction, and a restarted head (``ray_tpu.init(storage=...)``)
replays it.

Redis isn't in this environment (and a TPU-pod head has a local disk /
NFS mount), so the durable backend is a file journal — same recovery
contract, zero extra services.

Crash safety: journal records are framed (magic + length + CRC32 over the
pickled payload), so a process dying mid-append leaves a torn tail the
next replay detects, keeps everything before, and TRUNCATES away — the
write handle then appends after the last good record instead of after
torn garbage (which would poison every later record). Snapshot compaction
is fsync'd (file + directory) before the journal resets, so a crash
between the two never loses acknowledged writes.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from typing import Any, Dict, Tuple

# journal frame: magic + u32 payload length + u32 crc32(payload)
_MAGIC = b"\xabRJ1"
_FRAME_HDR = struct.Struct("<4sII")


class GcsStore:
    """put/delete land synchronously; load() replays at construction."""

    def put(self, table: str, key: Any, value: Any) -> None:
        raise NotImplementedError

    def delete(self, table: str, key: Any) -> None:
        raise NotImplementedError

    def load(self) -> Dict[str, Dict[Any, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStore(GcsStore):
    def put(self, table: str, key: Any, value: Any) -> None:
        pass

    def delete(self, table: str, key: Any) -> None:
        pass

    def load(self) -> Dict[str, Dict[Any, Any]]:
        return {}


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # e.g. directories not fsync-able on this fs


class FileStore(GcsStore):
    """Append-only framed journal + snapshot under a directory.

    Layout: ``snapshot.pkl`` (full table dump) + ``journal.pkl`` (framed
    records of pickled ("put"|"del", table, key, value) tuples since the
    snapshot). Writes append+flush; after ``compact_every`` journal
    records the state is re-snapshotted (fsync'd) and the journal
    truncated.
    """

    def __init__(self, path: str, compact_every: int = 1000):
        self.dir = path
        os.makedirs(path, exist_ok=True)
        self._snap_path = os.path.join(path, "snapshot.pkl")
        self._journal_path = os.path.join(path, "journal.pkl")
        self._compact_every = compact_every
        self._lock = threading.Lock()
        self._tables, good_end = self._replay()
        # torn/truncated tail from a crash mid-append: cut the journal
        # back to the last whole record BEFORE reopening for append —
        # appending after torn bytes would poison every later record
        if os.path.exists(self._journal_path) \
                and os.path.getsize(self._journal_path) > good_end:
            with open(self._journal_path, "r+b") as f:
                f.truncate(good_end)
        self._journal = open(self._journal_path, "ab")
        self._since_compact = 0

    def _replay(self) -> Tuple[Dict[str, Dict[Any, Any]], int]:
        """Replay snapshot + journal. Returns (tables, good_end): the
        journal byte offset after the last whole, checksum-valid record."""
        tables: Dict[str, Dict[Any, Any]] = {}
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    tables = pickle.load(f)
            except Exception:
                tables = {}
        good_end = 0
        if os.path.exists(self._journal_path):
            with open(self._journal_path, "rb") as f:
                head = f.read(4)
                f.seek(0)
                if head and head != _MAGIC:
                    # legacy unframed journal (pre-crash-safety format):
                    # raw pickle stream, replayed with per-record offset
                    # tracking so a torn tail still truncates cleanly
                    good_end = self._replay_legacy(f, tables)
                else:
                    good_end = self._replay_framed(f, tables)
        return tables, good_end

    @staticmethod
    def _apply(tables: Dict[str, Dict[Any, Any]], rec) -> None:
        op, table, key, value = rec
        t = tables.setdefault(table, {})
        if op == "put":
            t[key] = value
        else:
            t.pop(key, None)

    def _replay_framed(self, f, tables) -> int:
        good_end = 0
        while True:
            hdr = f.read(_FRAME_HDR.size)
            if len(hdr) < _FRAME_HDR.size:
                break  # clean EOF or torn header
            magic, length, crc = _FRAME_HDR.unpack(hdr)
            if magic != _MAGIC:
                break  # torn/garbage tail
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break  # crash mid-append: partial or corrupt payload
            try:
                rec = pickle.loads(payload)
            except Exception:
                break  # checksummed but unreadable (version skew): stop
            self._apply(tables, rec)
            good_end = f.tell()
        return good_end

    def _replay_legacy(self, f, tables) -> int:
        good_end = 0
        try:
            while True:
                rec = pickle.load(f)
                self._apply(tables, rec)
                good_end = f.tell()
        except Exception:  # torn tail (EOFError/UnpicklingError): keep prefix
            pass
        return good_end

    def _append(self, record: Tuple) -> None:
        payload = pickle.dumps(record)
        self._journal.write(_FRAME_HDR.pack(_MAGIC, len(payload),
                                            zlib.crc32(payload)))
        self._journal.write(payload)
        self._journal.flush()
        self._since_compact += 1
        if self._since_compact >= self._compact_every:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._tables, f)
            f.flush()
            os.fsync(f.fileno())  # snapshot durable BEFORE it replaces
        os.replace(tmp, self._snap_path)
        _fsync_dir(self.dir)  # the rename itself must survive a crash
        self._journal.close()
        self._journal = open(self._journal_path, "wb")
        # the truncation must be durable before new records append: a
        # crash here must not replay OLD journal records over the NEW
        # snapshot they are already folded into
        self._journal.flush()
        os.fsync(self._journal.fileno())
        self._since_compact = 0

    def put(self, table: str, key: Any, value: Any) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            self._append(("put", table, key, value))

    def delete(self, table: str, key: Any) -> None:
        with self._lock:
            self._tables.setdefault(table, {}).pop(key, None)
            self._append(("del", table, key, None))

    def load(self) -> Dict[str, Dict[Any, Any]]:
        with self._lock:
            return {t: dict(kv) for t, kv in self._tables.items()}

    def close(self) -> None:
        with self._lock:
            try:
                self._journal.flush()
                os.fsync(self._journal.fileno())
            except (OSError, ValueError):
                pass
            try:
                self._journal.close()
            except Exception:
                pass
