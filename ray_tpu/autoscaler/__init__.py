"""Autoscaler: reconcile cluster size against pending resource demand.

Reference: autoscaler v2 (python/ray/autoscaler/v2/autoscaler.py:42 — a
periodic reconciler reading demand from GCS load reports and instance
state from a cloud provider) and the v1 StandardAutoscaler
(_private/autoscaler.py:172). Re-designed for TPU fleets: a node is a
*host joining over TCP* (the ``python -m ray_tpu start`` daemon), and the
cloud-provider seam is :class:`NodeProvider` — the local subprocess
provider is fully functional (used in tests and single-machine
elasticity); a TPU-slice provider maps node requests onto GKE/Queued
Resources via an operator-supplied launcher.
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    LocalNodeProvider,
    NodeProvider,
    TPUQueuedResourceProvider,
    TPUSliceProvider,
)

__all__ = ["Autoscaler", "AutoscalerConfig", "NodeProvider",
           "LocalNodeProvider", "TPUSliceProvider",
           "TPUQueuedResourceProvider"]
