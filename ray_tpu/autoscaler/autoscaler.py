"""The reconciler: demand in, launch/terminate decisions out.

Reference shape: autoscaler v2's Reconciler
(python/ray/autoscaler/v2/instance_manager/reconciler.py via
autoscaler.py:42 update()) — each tick reads (1) pending resource demand,
(2) current instance states, and computes a target; plus v1's idle-node
termination (_private/autoscaler.py StandardAutoscaler._update). Demand
here comes straight from the head scheduler's pending queues
(core/scheduler.py pending_demand()), not a gossip pipeline — the
single-head design makes load reports exact.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    idle_timeout_s: float = 30.0
    interval_s: float = 1.0
    # fraction of outstanding demand to satisfy per tick (v1's
    # upscaling_speed: 1.0 = launch for all unplaced work at once)
    upscaling_speed: float = 1.0
    # resources each launched worker contributes (capacity planning unit)
    node_config: Dict = field(default_factory=lambda: {"num_cpus": 2})


class Autoscaler:
    """Periodic reconciler bound to a Head + NodeProvider."""

    def __init__(self, head, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self.head = head
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._idle_since: Dict[str, float] = {}   # node_hex -> ts
        self._stopped = threading.Event()
        self.num_launches = 0
        self.num_terminations = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    # ---- sizing math ------------------------------------------------------
    def _node_capacity(self) -> Dict[str, float]:
        cap = {}
        nc = self.config.node_config
        if nc.get("num_cpus"):
            cap["CPU"] = float(nc["num_cpus"])
        if nc.get("num_tpus"):
            cap["TPU"] = float(nc["num_tpus"])
        for k, v in (nc.get("resources") or {}).items():
            cap[k] = float(v)
        return cap or {"CPU": 1.0}

    def _workers_for_demand(self, demand: List[Dict[str, float]]) -> int:
        """Bin-pack pending asks onto fresh nodes of node_config capacity
        (first-fit; the v2 resource_demand_scheduler analog)."""
        cap = self._node_capacity()
        bins: List[Dict[str, float]] = []
        for ask in demand:
            ask = {k: v for k, v in ask.items() if v > 0}
            if not ask:
                continue
            if any(ask.get(k, 0) > cap.get(k, 0) for k in ask):
                continue  # infeasible on this node shape: skip (and log?)
            placed = False
            for b in bins:
                if all(b.get(k, 0) >= v for k, v in ask.items()):
                    for k, v in ask.items():
                        b[k] = b[k] - v
                    placed = True
                    break
            if not placed:
                fresh = dict(cap)
                for k, v in ask.items():
                    fresh[k] = fresh.get(k, 0) - v
                bins.append(fresh)
        return len(bins)

    # ---- reconcile tick ---------------------------------------------------
    def update(self) -> None:
        """One reconcile pass (public for tests; the loop calls it).

        Size accounting: ``provider_count`` (instances the provider holds,
        joined or still booting) vs ``alive_workers`` (nodes registered in
        GCS). in-flight = provider_count - alive_workers, so repeated
        ticks don't double-launch while daemons boot.
        """
        cfg = self.config
        now = time.monotonic()
        provider_count = len(self.provider.non_terminated_nodes())
        head_hex = self.head.head_node.hex
        alive_workers = [n for n in self.head.gcs.alive_nodes()
                         if n.hex != head_hex]

        from ray_tpu.util import events as events_mod

        demand = self.head.scheduler.pending_demand()
        want = int(math.ceil(
            self._workers_for_demand(demand) * cfg.upscaling_speed))
        target = max(cfg.min_workers,
                     min(cfg.max_workers, len(alive_workers) + want))
        # ---- scale up ----
        launching = max(0, target - provider_count)
        if launching:
            events_mod.emit(
                "INFO", events_mod.SOURCE_AUTOSCALER,
                f"scaling up: launching {launching} node(s) "
                f"(demand={len(demand)} asks, alive={len(alive_workers)}, "
                f"target={target})", entity_id="autoscaler",
                launching=launching, target=target,
                pending_demand=len(demand))
        for _ in range(launching):
            self.provider.create_node(dict(cfg.node_config))
            self.num_launches += 1

        # ---- scale down (idle nodes beyond min_workers) ----
        idle = set(self.head.scheduler.idle_nodes())
        idle.discard(head_hex)
        for h in list(self._idle_since):
            if h not in idle:
                del self._idle_since[h]
        for h in idle:
            self._idle_since.setdefault(h, now)
        expendable = len(alive_workers) - cfg.min_workers
        if expendable > 0 and not demand:
            victims = sorted(
                (h for h, t0 in self._idle_since.items()
                 if now - t0 >= cfg.idle_timeout_s),
                key=lambda h: self._idle_since[h])[:expendable]
            for h in victims:
                pid = self._provider_id_for(h)
                if pid is not None:
                    idle_s = now - self._idle_since[h]
                    events_mod.emit(
                        "INFO", events_mod.SOURCE_AUTOSCALER,
                        f"terminating idle node {h[:8]} "
                        f"(idle {idle_s:.1f}s >= {cfg.idle_timeout_s}s)",
                        entity_id=h, provider_id=pid, idle_s=idle_s)
                    self.provider.terminate_node(pid)
                    self.num_terminations += 1
                    del self._idle_since[h]

    def _provider_id_for(self, node_hex: str) -> Optional[str]:
        """Map a cluster node id to a provider instance id via labels."""
        info = self.head.gcs.nodes.get(node_hex)
        if info is None or not getattr(info, "alive", False):
            return None
        pid = (info.labels or {}).get("provider_id")
        if pid and pid in self.provider.non_terminated_nodes():
            return pid
        return None

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("ray_tpu.autoscaler")
        last_err = None
        while not self._stopped.wait(self.config.interval_s):
            try:
                self.update()
                last_err = None
            except Exception as e:  # next tick retries; log distinct errors
                if repr(e) != last_err:
                    last_err = repr(e)
                    log.exception("autoscaler reconcile failed "
                                  "(will keep retrying): %s", e)

    def stop(self, terminate_nodes: bool = True) -> None:
        self._stopped.set()
        self._thread.join(timeout=2.0)  # event-paced loop: exits promptly
        if terminate_nodes:
            self.provider.shutdown()
