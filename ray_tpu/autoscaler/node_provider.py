"""Node providers: the cloud seam of the autoscaler.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider interface:
create_node/terminate_node/non_terminated_nodes) and the per-cloud
implementations under python/ray/autoscaler/_private/. Here the
interface is minimal and synchronous; the reconciler serializes calls.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional


class NodeProvider:
    """Create/terminate worker nodes. Implementations must be idempotent
    on terminate and report only their own (non-head) nodes."""

    def create_node(self, node_config: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def is_running(self, provider_id: str) -> bool:
        return provider_id in self.non_terminated_nodes()

    def shutdown(self) -> None:
        for pid in list(self.non_terminated_nodes()):
            self.terminate_node(pid)


class LocalNodeProvider(NodeProvider):
    """Worker nodes as local ``python -m ray_tpu start`` daemon processes
    joining the head over TCP — the autoscaler analog of the reference's
    'local' provider, and the test double for cloud providers (every
    launched node is a REAL separate-process node daemon)."""

    def __init__(self, head_address, cluster_key_hex: str):
        self._address = f"{head_address[0]}:{head_address[1]}"
        self._key = cluster_key_hex
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def create_node(self, node_config: dict) -> str:
        import json

        provider_id = f"local-{uuid.uuid4().hex[:8]}"
        cmd = [sys.executable, "-m", "ray_tpu", "start",
               "--address", self._address, "--key", self._key,
               # the provider_id label is how the reconciler maps a
               # cluster node back to this instance for termination
               "--labels", json.dumps({"provider_id": provider_id}),
               # explicit counts — never auto-detect (a co-located node
               # already advertises the TPU chips)
               "--num-cpus", str(node_config.get("num_cpus", 1)),
               "--num-tpus", str(node_config.get("num_tpus", 0))]
        if node_config.get("resources"):
            cmd += ["--resources", json.dumps(node_config["resources"])]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU worker nodes
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, env=env, start_new_session=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[provider_id] = proc
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(provider_id, None)
        if proc is None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + 3.0
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.05)
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [pid for pid, proc in self._procs.items()
                    if proc.poll() is None]


class TPUSliceProvider(NodeProvider):
    """TPU-slice provisioning seam (injected callables).

    Zero-egress environments can't call cloud APIs, so actual provisioning
    is delegated to operator-supplied callables — e.g. wrappers over
    ``gcloud compute tpus queued-resources create`` or a KubeRay-style CRD
    reconciler. The autoscaler treats slices as atomic nodes: one
    create_node call = one slice request (the TPU analog of the
    reference's per-VM cloud providers). For the full Queued-Resources
    shape see :class:`TPUQueuedResourceProvider`.
    """

    def __init__(self, launch_fn: Callable[[dict], str],
                 terminate_fn: Callable[[str], None],
                 list_fn: Callable[[], List[str]]):
        self._launch = launch_fn
        self._terminate = terminate_fn
        self._list = list_fn

    def create_node(self, node_config: dict) -> str:
        return self._launch(node_config)

    def terminate_node(self, provider_id: str) -> None:
        self._terminate(provider_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._list())


# accelerator type -> (chips per host, total chips); topology label is the
# type's own chip grid (reference: accelerators/tpu.py pod shapes)
_TPU_SHAPES = {
    "v4-8": (4, 4), "v4-16": (4, 8), "v4-32": (4, 16),
    "v5litepod-4": (4, 4), "v5litepod-8": (8, 8), "v5litepod-16": (4, 16),
    "v5litepod-32": (4, 32), "v5litepod-64": (4, 64),
    "v5p-8": (4, 4), "v5p-16": (4, 8),
    "v6e-4": (4, 4), "v6e-8": (8, 8), "v6e-16": (4, 16),
    "v6e-64": (4, 64), "v6e-256": (4, 256),
}


class TPUQueuedResourceProvider(NodeProvider):
    """GCP Queued-Resources slice provider (reference: the cloud-provider
    role of python/ray/autoscaler/_private/gcp/ + the TPU pod semantics of
    accelerators/tpu.py:71).

    One ``create_node`` = one queued-resource request for a whole slice.
    Every host of a granted slice bootstraps (via the startup script this
    provider composes) as a node daemon carrying the slice topology as
    scheduler labels:

        ray-tpu-slice=<qr name>, ray-tpu-accelerator=<type>,
        ray-tpu-worker=<host index>

    plus the ``TPU-<type>-head`` resource on worker 0 — the label set
    gang-scheduling placement groups key on.

    ``runner`` executes the gcloud invocations and returns stdout; the
    default shells out, tests inject a fake (this box has zero egress).
    The QR lifecycle (WAITING_FOR_RESOURCES -> PROVISIONING -> ACTIVE |
    SUSPENDED/FAILED) is polled via ``list``; only non-terminal QRs count
    as non_terminated (the autoscaler keeps demand pending meanwhile).
    """

    def __init__(self, head_address, cluster_key_hex: str, *,
                 project: str, zone: str,
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 runner: Optional[Callable[[List[str]], str]] = None):
        self._address = f"{head_address[0]}:{head_address[1]}"
        self._key = cluster_key_hex
        self._project = project
        self._zone = zone
        self._runtime = runtime_version
        self._runner = runner or self._shell
        self._lock = threading.Lock()
        self._requested: Dict[str, dict] = {}  # qr name -> node_config

    @staticmethod
    def _shell(cmd: List[str]) -> str:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=300)
        if out.returncode != 0:
            raise RuntimeError(f"{' '.join(cmd)} failed: {out.stderr}")
        return out.stdout

    # ---- slice math ------------------------------------------------------

    @staticmethod
    def slice_shape(accelerator_type: str):
        """(chips_per_host, total_chips, num_hosts) for a type."""
        per_host, total = _TPU_SHAPES.get(accelerator_type, (4, 4))
        return per_host, total, max(1, total // per_host)

    def startup_script(self, qr_name: str, accelerator_type: str) -> str:
        """Per-host bootstrap: join the head with slice-topology labels.
        TPU_WORKER_ID is set by the TPU runtime on every pod host."""
        import json

        per_host, _total, _hosts = self.slice_shape(accelerator_type)
        labels = {
            "ray-tpu-slice": qr_name,
            "ray-tpu-accelerator": accelerator_type,
            "ray-tpu-worker": "${TPU_WORKER_ID}",
        }
        head_res = json.dumps({f"TPU-{accelerator_type}-head": 1})
        # The labels JSON must ride inside DOUBLE quotes so the shell
        # expands ${TPU_WORKER_ID} per host (single quotes would register
        # every host with the literal string '${TPU_WORKER_ID}').
        labels_sh = json.dumps(labels).replace('"', '\\"')
        return (
            "#!/bin/bash\n"
            f"RES='{{}}'\n"
            f"if [ \"${{TPU_WORKER_ID}}\" = \"0\" ]; then RES='{head_res}'; fi\n"
            f"python -m ray_tpu start --address {self._address} "
            f"--key {self._key} --num-tpus {per_host} "
            f"--resources \"$RES\" "
            f"--labels \"{labels_sh}\"\n"
        )

    # ---- provider interface ---------------------------------------------

    def create_node(self, node_config: dict) -> str:
        acc = node_config.get("accelerator_type", "v5litepod-4")
        qr_name = f"raytpu-qr-{uuid.uuid4().hex[:8]}"
        cmd = [
            "gcloud", "compute", "tpus", "queued-resources", "create",
            qr_name,
            f"--project={self._project}", f"--zone={self._zone}",
            f"--node-id={qr_name}-node",
            f"--accelerator-type={acc}",
            f"--runtime-version={self._runtime}",
            "--metadata-from-file",
            f"startup-script={self._write_script(qr_name, acc)}",
        ]
        if node_config.get("spot"):
            cmd.append("--spot")
        if node_config.get("reserved"):
            cmd.append("--reserved")
        self._runner(cmd)
        with self._lock:
            self._requested[qr_name] = dict(node_config)
        return qr_name

    def _write_script(self, qr_name: str, acc: str) -> str:
        import tempfile

        path = os.path.join(tempfile.gettempdir(),
                            f"raytpu_qr_{qr_name}.sh")
        with open(path, "w") as f:
            f.write(self.startup_script(qr_name, acc))
        return path

    # delete errors that mean the QR is already gone / already going:
    # retrying is pointless and raising would abort the reconciler's
    # whole scale-down pass (other victims never terminate)
    _GONE_MARKERS = ("not_found", "notfound", "404", "409", "conflict",
                     "already", "deleting", "does not exist")

    def terminate_node(self, provider_id: str) -> None:
        try:
            self._runner([
                "gcloud", "compute", "tpus", "queued-resources", "delete",
                provider_id, f"--project={self._project}",
                f"--zone={self._zone}", "--quiet", "--force"])
        except Exception as e:  # noqa: BLE001 — classify, don't mask
            msg = str(e).lower()
            if not any(m in msg for m in self._GONE_MARKERS):
                raise
            # already deleted / delete in progress: converge silently
        with self._lock:
            self._requested.pop(provider_id, None)

    def non_terminated_nodes(self) -> List[str]:
        import json

        try:
            out = self._runner([
                "gcloud", "compute", "tpus", "queued-resources", "list",
                f"--project={self._project}", f"--zone={self._zone}",
                "--format=json"])
        except Exception:
            # transient list/describe failure (gcloud timeouts are the
            # common QR-devops papercut): serve the last good view so one
            # blip doesn't zero the provider count and double-launch.
            # Never-succeeded listing still raises (misconfig, fail fast).
            cached = getattr(self, "_last_alive", None)
            if cached is None:
                raise
            return list(cached)
        alive = []
        for qr in json.loads(out or "[]"):
            name = qr.get("name", "").rsplit("/", 1)[-1]
            state = (qr.get("state") or {}).get("state", "")
            if state not in ("SUSPENDED", "FAILED", "DELETING"):
                alive.append(name)
        self._last_alive = list(alive)
        return alive
