"""Node providers: the cloud seam of the autoscaler.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider interface:
create_node/terminate_node/non_terminated_nodes) and the per-cloud
implementations under python/ray/autoscaler/_private/. Here the
interface is minimal and synchronous; the reconciler serializes calls.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional


class NodeProvider:
    """Create/terminate worker nodes. Implementations must be idempotent
    on terminate and report only their own (non-head) nodes."""

    def create_node(self, node_config: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def is_running(self, provider_id: str) -> bool:
        return provider_id in self.non_terminated_nodes()

    def shutdown(self) -> None:
        for pid in list(self.non_terminated_nodes()):
            self.terminate_node(pid)


class LocalNodeProvider(NodeProvider):
    """Worker nodes as local ``python -m ray_tpu start`` daemon processes
    joining the head over TCP — the autoscaler analog of the reference's
    'local' provider, and the test double for cloud providers (every
    launched node is a REAL separate-process node daemon)."""

    def __init__(self, head_address, cluster_key_hex: str):
        self._address = f"{head_address[0]}:{head_address[1]}"
        self._key = cluster_key_hex
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def create_node(self, node_config: dict) -> str:
        import json

        provider_id = f"local-{uuid.uuid4().hex[:8]}"
        cmd = [sys.executable, "-m", "ray_tpu", "start",
               "--address", self._address, "--key", self._key,
               # the provider_id label is how the reconciler maps a
               # cluster node back to this instance for termination
               "--labels", json.dumps({"provider_id": provider_id}),
               # explicit counts — never auto-detect (a co-located node
               # already advertises the TPU chips)
               "--num-cpus", str(node_config.get("num_cpus", 1)),
               "--num-tpus", str(node_config.get("num_tpus", 0))]
        if node_config.get("resources"):
            cmd += ["--resources", json.dumps(node_config["resources"])]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU worker nodes
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, env=env, start_new_session=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[provider_id] = proc
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(provider_id, None)
        if proc is None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + 3.0
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.05)
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [pid for pid, proc in self._procs.items()
                    if proc.poll() is None]


class TPUSliceProvider(NodeProvider):
    """TPU-slice provisioning seam (GKE node pools / Queued Resources).

    Zero-egress environments can't call cloud APIs, so actual provisioning
    is delegated to operator-supplied callables — e.g. wrappers over
    ``gcloud compute tpus queued-resources create`` or a KubeRay-style CRD
    reconciler. The autoscaler treats slices as atomic nodes: one
    create_node call = one slice request (the TPU analog of the
    reference's per-VM cloud providers).
    """

    def __init__(self, launch_fn: Callable[[dict], str],
                 terminate_fn: Callable[[str], None],
                 list_fn: Callable[[], List[str]]):
        self._launch = launch_fn
        self._terminate = terminate_fn
        self._list = list_fn

    def create_node(self, node_config: dict) -> str:
        return self._launch(node_config)

    def terminate_node(self, provider_id: str) -> None:
        self._terminate(provider_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._list())
