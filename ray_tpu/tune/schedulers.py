"""Trial schedulers: FIFO, ASHA, HyperBand, Median-stopping, PBT.

Reference: python/ray/tune/schedulers/ (async_hyperband.py ASHA, pbt.py,
median_stopping_rule.py, trial_scheduler.py).
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric, mode) -> None:
        self.metric = self.metric or metric
        self.mode = self.mode or mode

    def _score(self, result: Dict[str, Any]) -> float:
        v = result.get(self.metric)
        if v is None:
            return float("-inf")
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result) -> str:
        return self.CONTINUE

    def on_trial_complete(self, controller, trial, result) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: async_hyperband.py). Rungs at
    grace_period * reduction_factor^k; a trial stops at a rung if its score
    is below the top 1/reduction_factor quantile of completed rung entries.
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3, brackets: int = 1):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones
        self.rungs: List[float] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_scores: Dict[float, List[float]] = defaultdict(list)
        self._trial_rung: Dict[str, int] = {}

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if t >= self.max_t:
            return self.STOP
        rung_idx = self._trial_rung.get(trial.trial_id, 0)
        action = self.CONTINUE
        while rung_idx < len(self.rungs) and t >= self.rungs[rung_idx]:
            milestone = self.rungs[rung_idx]
            scores = self.rung_scores[milestone]
            scores.append(score)
            k = max(1, int(len(scores) / self.rf))
            cutoff = sorted(scores, reverse=True)[k - 1]
            if score < cutoff:
                action = self.STOP
            rung_idx += 1
        self._trial_rung[trial.trial_id] = rung_idx
        return action


# HyperBand's synchronous brackets add little over ASHA in practice; the
# reference ships both — we expose HyperBandScheduler as multi-bracket ASHA.
class HyperBandScheduler(AsyncHyperBandScheduler):
    pass


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average best score is below the median of
    other trials at the same step (reference: median_stopping_rule.py)."""

    def __init__(self, metric=None, mode=None,
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = defaultdict(list)

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        self._history[trial.trial_id].append(score)
        if t < self.grace_period:
            return self.CONTINUE
        means = [float(np.mean(v)) for k, v in self._history.items()
                 if k != trial.trial_id and v]
        if len(means) < self.min_samples:
            return self.CONTINUE
        my_mean = float(np.mean(self._history[trial.trial_id]))
        if my_mean < float(np.median(means)):
            return self.STOP
        return self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: pbt.py): at each perturbation interval, bottom-
    quantile trials clone the state of a top-quantile trial (exploit) and
    perturb hyperparameters (explore). Requires checkpointable trainables.
    """

    def __init__(self, metric=None, mode=None,
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._latest: Dict[str, float] = {}

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .sample import Domain

        new = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob:
                if isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
                elif isinstance(spec, Domain):
                    new[key] = spec.sample(np.random.RandomState(
                        self._rng.randint(0, 2**31)))
                elif callable(spec):
                    new[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(new.get(key), (int, float)) and not isinstance(
                        new.get(key), bool):
                    new[key] = type(new[key])(new[key] * factor)
        return new

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        self._observe(trial, t, score)
        self._latest[trial.trial_id] = score
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        scores = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(scores)
        if n < 2:
            return self.CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in scores[:k]]
        top = [tid for tid, _ in scores[-k:]]
        if trial.trial_id in bottom and trial.trial_id not in top:
            donor_id = self._rng.choice(top)
            donor = controller.get_trial(donor_id)
            if donor is not None and donor.checkpoint_path:
                new_config = self._mutate(donor.config)
                controller.exploit_trial(trial, donor, new_config)
        return self.CONTINUE

    def _observe(self, trial, t, score) -> None:
        """Hook for model-based variants (PB2)."""


class PB2(PopulationBasedTraining):
    """PBT with GP-guided exploration (reference: pb2.py / the PB2 paper
    "Provably Efficient Online Hyperparameter Optimization with
    Population-Based Bandits"): instead of random perturbation, fit a GP
    to (hyperparams -> score improvement) observations across the
    population and pick the next config by UCB within bounds. Numpy-only
    GP (RBF kernel) — no sklearn/GPy dependency.
    """

    def __init__(self, metric=None, mode=None,
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 seed: Optional[int] = None):
        super().__init__(metric, mode, time_attr, perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction,
                         resample_probability=0.0, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds="
                             "{name: (low, high)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self._np_rng = np.random.RandomState(seed or 0)
        # (normalized config vector, score delta) observations
        self._X: list = []
        self._y: list = []
        self._prev_score: Dict[str, float] = {}

    # ---- observation stream ----
    def _vec(self, config: Dict[str, Any]) -> np.ndarray:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return np.asarray(out, np.float64)

    def _observe(self, trial, t, score) -> None:
        prev = self._prev_score.get(trial.trial_id)
        self._prev_score[trial.trial_id] = score
        if prev is None:
            return
        self._X.append(self._vec(trial.config))
        self._y.append(score - prev)
        if len(self._X) > 512:  # bound GP cost
            self._X = self._X[-512:]
            self._y = self._y[-512:]

    # ---- GP-UCB selection replaces random mutation ----
    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        n_cand = 128
        cand = self._np_rng.uniform(size=(n_cand, len(self.bounds)))
        if len(self._X) >= 4:
            X = np.stack(self._X)
            y = np.asarray(self._y, np.float64)
            ystd = y.std() or 1.0
            yn = (y - y.mean()) / ystd

            def rbf(a, b, ls=0.3):
                d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
                return np.exp(-0.5 * d2 / ls ** 2)

            K = rbf(X, X) + 1e-2 * np.eye(len(X))
            Ks = rbf(cand, X)
            try:
                Kinv_y = np.linalg.solve(K, yn)
                mu = Ks @ Kinv_y
                Kinv_Ks = np.linalg.solve(K, Ks.T)
                var = np.maximum(1e-12, 1.0 - (Ks * Kinv_Ks.T).sum(-1))
                ucb = mu + self.kappa * np.sqrt(var)
                best = cand[int(np.argmax(ucb))]
            except np.linalg.LinAlgError:
                best = cand[0]
        else:
            best = cand[0]
        for i, (k, (lo, hi)) in enumerate(self.bounds.items()):
            val = lo + best[i] * (hi - lo)
            if isinstance(config.get(k), int):
                val = int(round(val))
            new[k] = val
        return new
