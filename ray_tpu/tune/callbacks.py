"""Tune callbacks + per-trial loggers.

Reference: python/ray/tune/callback.py (Callback hook interface the
controller invokes on trial lifecycle events) and
tune/logger/{json,csv,tensorboardx}.py (per-trial result sinks). The
TensorBoard logger is omitted (no tensorboardX in this environment); the
JSON/CSV loggers produce the same ``result.json`` / ``progress.csv``
files the reference tooling reads.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Subclass and override the hooks you need."""

    def on_trial_start(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_trial_result(self, iteration: int, trials: List, trial,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_trial_error(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_checkpoint(self, iteration: int, trials: List, trial,
                      checkpoint_path: str) -> None:
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


def _json_default(v):
    try:
        import numpy as np

        if isinstance(v, (np.integer, np.floating)):
            return v.item()
        if isinstance(v, np.ndarray):
            return v.tolist()
    except Exception:
        pass
    return str(v)


class JsonLoggerCallback(Callback):
    """Appends each result as a JSON line to <trial_dir>/result.json
    (reference: tune/logger/json.py)."""

    def on_trial_result(self, iteration, trials, trial, result) -> None:
        if not trial.trial_dir:
            return
        os.makedirs(trial.trial_dir, exist_ok=True)
        with open(os.path.join(trial.trial_dir, "result.json"), "a") as f:
            json.dump(result, f, default=_json_default)
            f.write("\n")


class CSVLoggerCallback(Callback):
    """Appends results to <trial_dir>/progress.csv; the header is the
    first result's scalar keys (reference: tune/logger/csv.py)."""

    def __init__(self):
        self._keys: Dict[str, List[str]] = {}

    def on_trial_result(self, iteration, trials, trial, result) -> None:
        if not trial.trial_dir:
            return
        os.makedirs(trial.trial_dir, exist_ok=True)
        path = os.path.join(trial.trial_dir, "progress.csv")
        scalars = {k: v for k, v in result.items()
                   if isinstance(v, (int, float, str, bool))}
        keys = self._keys.get(trial.trial_id)
        fresh = keys is None
        if fresh:
            keys = self._keys[trial.trial_id] = sorted(scalars)
        with open(path, "a", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys, extrasaction="ignore")
            if fresh:
                w.writeheader()
            w.writerow(scalars)


DEFAULT_CALLBACKS = (JsonLoggerCallback, CSVLoggerCallback)
