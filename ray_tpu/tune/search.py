"""Searchers: BasicVariantGenerator (grid+random), Searcher plugin API,
ConcurrencyLimiter.

Reference: python/ray/tune/search/ (basic_variant.py, searcher.py,
concurrency_limiter.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import sample as S


class Searcher:
    """Plugin interface (reference: search/searcher.py Searcher)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric, mode, config) -> bool:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random sampling
    (reference: basic_variant.py)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 num_samples: int = 1, seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._rng = np.random.RandomState(seed)
        self._points = list(points_to_evaluate or [])
        self._queue: List[Dict[str, Any]] = []
        self._generated = False

    def set_space(self, space: Dict[str, Any]) -> None:
        self._space = space
        self._generated = False

    def _generate(self) -> None:
        self._queue = []
        for point in self._points:
            cfg = S.resolve(self._space, self._rng)
            cfg.update(point)
            self._queue.append(cfg)
        grid_variants = S.expand_grid(self._space)
        for _ in range(self._num_samples):
            for variant in grid_variants:
                self._queue.append(S.resolve(variant, self._rng))
        self._generated = True

    def total_trials(self) -> int:
        if not self._generated:
            self._generate()
        return len(self._queue) + self._consumed if hasattr(
            self, "_consumed") else len(self._queue)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._generated:
            self._generate()
        if not self._queue:
            return None
        return self._queue.pop(0)


class SearchGenerator(Searcher):
    """Adapts a Searcher producing one config per suggest() to a bounded
    number of samples."""

    def __init__(self, searcher: Searcher, space: Dict[str, Any],
                 num_samples: int):
        super().__init__(searcher.metric, searcher.mode)
        self._searcher = searcher
        self._space = space
        self._remaining = num_samples

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self._searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._remaining <= 0:
            return None
        cfg = self._searcher.suggest(trial_id)
        if cfg is None:
            return None
        self._remaining -= 1
        merged = S.resolve(self._space, np.random.RandomState())
        merged.update(cfg)
        return merged

    def on_trial_result(self, trial_id, result):
        self._searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._searcher.on_trial_complete(trial_id, result, error)


class ConcurrencyLimiter(Searcher):
    """Caps concurrent suggestions (reference: concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return "PENDING"  # sentinel: try again later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "PENDING":
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class TPESearch(Searcher):
    """Dependency-free Tree-structured Parzen Estimator (TPE-lite).

    The in-repo model-based searcher (and OptunaSearch's offline
    fallback sampler): observations split into a good fraction
    (``gamma``) and the rest; numeric dimensions score candidates by the
    density ratio l(x)/g(x) of Gaussian mixtures centered on the good /
    bad observations (log-domains fit in log10 space), categoricals by
    smoothed count ratios. TPE factorizes per dimension, so each
    dimension takes the argmax over its own candidate set
    (Bergstra et al. 2011; reference adapter surface:
    python/ray/tune/search/optuna/optuna_search.py).
    """

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 seed: Optional[int] = None, n_startup_trials: int = 12,
                 gamma: float = 0.15, n_candidates: int = 48,
                 exploration_eps: float = 0.08,
                 points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__(metric, mode)
        self._space = dict(space or {})
        self._rng = np.random.RandomState(seed)
        self._n_startup = n_startup_trials
        self._gamma = gamma
        self._n_cand = n_candidates
        self._eps = exploration_eps  # random-restart probe probability
        self._points = list(points_to_evaluate or [])
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._obs: List[tuple] = []  # (config, minimized value)

    def set_space(self, space: Dict[str, Any]) -> None:
        self._space = dict(space)

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _bounds(dom):
        lo, hi = float(dom.lower), float(dom.upper)
        if getattr(dom, "log", False):
            lo, hi = np.log10(lo), np.log10(hi)
        return lo, hi

    @staticmethod
    def _to_z(dom, v):
        return float(np.log10(v)) if getattr(dom, "log", False) else float(v)

    @staticmethod
    def _from_z(dom, z):
        v = 10.0 ** z if getattr(dom, "log", False) else z
        if isinstance(dom, S.Integer):
            v = int(round(v))
            return int(np.clip(v, dom.lower, dom.upper))
        return float(np.clip(v, dom.lower, dom.upper))

    @staticmethod
    def _log_mixture(x, centers, bws):
        # log of a uniform-weight Gaussian mixture density at x
        # (per-center bandwidths: the uniform-prior component is wide)
        d = (x[:, None] - centers[None, :]) / bws[None, :]
        log_terms = -0.5 * d * d - np.log(bws[None, :] * np.sqrt(2 * np.pi))
        m = log_terms.max(axis=1)
        return m + np.log(
            np.mean(np.exp(log_terms - m[:, None]), axis=1))

    @staticmethod
    def _nn_bandwidths(z, span, scale=1.5, floor_frac=1 / 50):
        """Per-point Parzen bandwidth = distance to the nearest other
        point (as in optuna's TPE): shrinks as observations cluster, so
        refinement gets finer instead of repeating the mixture mode —
        a fixed global bandwidth makes argmax(l/g) crawl."""
        if len(z) == 1:
            return np.array([span * 0.5])
        order = np.argsort(z)
        zs = z[order]
        d = np.empty(len(z))
        for rank, i in enumerate(order):
            left = zs[rank] - zs[rank - 1] if rank > 0 else np.inf
            right = zs[rank + 1] - zs[rank] if rank < len(z) - 1 else np.inf
            d[i] = min(left, right)
        return np.clip(d * scale, span * floor_frac, span)

    def _suggest_numeric(self, dom, good, bad):
        lo, hi = self._bounds(dom)
        span = max(hi - lo, 1e-12)
        gz = np.array([self._to_z(dom, v) for v in good])
        bz = np.array([self._to_z(dom, v) for v in bad])
        g_bw = self._nn_bandwidths(gz, span)
        # l(x) includes the uniform prior as a wide component (optuna's
        # TPE does the same) so exploitation never fully kills coverage
        g_centers = np.append(gz, 0.5 * (lo + hi))
        g_bws = np.append(g_bw, span)
        # candidates: jittered good points (each with its own bandwidth)
        # plus a quarter from the prior — pure exploitation stalls
        n_prior = max(1, self._n_cand // 4)
        n_good = self._n_cand - n_prior
        ci = self._rng.randint(0, len(gz), n_good)
        cands = np.concatenate([
            gz[ci] + self._rng.normal(0.0, 1.0, n_good) * g_bw[ci],
            self._rng.uniform(lo, hi, n_prior),
        ])
        cands = np.clip(cands, lo, hi)
        score = self._log_mixture(cands, g_centers, g_bws)
        if len(bz):
            score = score - self._log_mixture(
                cands, bz, self._nn_bandwidths(bz, span))
        return self._from_z(dom, float(cands[int(np.argmax(score))]))

    def _suggest_categorical(self, dom, good, bad):
        cats = list(dom.categories)

        def smoothed(vals):
            counts = np.array(
                [1.0 + sum(1 for v in vals if v == c) for c in cats])
            return counts / counts.sum()

        score = np.log(smoothed(good)) - np.log(smoothed(bad))
        return cats[int(np.argmax(score))]

    # -- Searcher API -----------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._points:
            cfg = S.resolve(self._space, self._rng)
            cfg.update(self._points.pop(0))
        elif (len(self._obs) < self._n_startup
              or self._rng.rand() < self._eps):
            # startup phase / exploration probe: a pure prior sample
            cfg = S.resolve(self._space, self._rng)
        else:
            obs = sorted(self._obs, key=lambda o: o[1])
            n_good = max(1, int(np.ceil(self._gamma * len(obs))))
            good_cfgs = [c for c, _ in obs[:n_good]]
            bad_cfgs = [c for c, _ in obs[n_good:]]
            cfg = {}
            for key, dom in self._space.items():
                if not isinstance(dom, S.Domain):
                    cfg[key] = dom  # constant
                    continue
                good = [c[key] for c in good_cfgs if key in c]
                bad = [c[key] for c in bad_cfgs if key in c]
                if not good:
                    cfg[key] = dom.sample(self._rng)
                elif isinstance(dom, S.Categorical):
                    cfg[key] = self._suggest_categorical(dom, good, bad)
                elif isinstance(dom, (S.Float, S.Integer)):
                    cfg[key] = self._suggest_numeric(dom, good, bad)
                else:  # Function domains: no density model
                    cfg[key] = dom.sample(self._rng)
        self._suggested[trial_id] = cfg
        return dict(cfg)

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        v = float(value)
        if self.mode == "max":
            v = -v  # minimize internally
        self._obs.append((cfg, v))


class OptunaSearch(Searcher):
    """Optuna adapter (reference:
    python/ray/tune/search/optuna/optuna_search.py OptunaSearch): bridges
    tune/sample.py domains to an optuna Study via ask/tell. When optuna
    is not importable (this zero-egress image), the same adapter surface
    runs on the in-repo :class:`TPESearch` sampler, so model-based search
    works offline and swaps to real optuna transparently when present."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 seed: Optional[int] = None, n_startup_trials: int = 10,
                 points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__(metric, mode)
        self._space = dict(space or {})
        self._seed = seed
        try:  # pragma: no cover - optuna absent in this image
            import optuna

            self._optuna = optuna
        except ImportError:
            self._optuna = None
            self._fallback = TPESearch(
                space, metric=metric, mode=mode, seed=seed,
                n_startup_trials=n_startup_trials,
                points_to_evaluate=points_to_evaluate)
        self._study = None
        self._trials: Dict[str, Any] = {}

    def set_space(self, space: Dict[str, Any]) -> None:
        self._space = dict(space)
        if self._optuna is None:
            self._fallback.set_space(space)

    def set_search_properties(self, metric, mode, config) -> bool:
        ok = super().set_search_properties(metric, mode, config)
        if self._optuna is None:
            self._fallback.set_search_properties(metric, mode, config)
        return ok

    # -- real-optuna path (pragma: exercised only where optuna exists) ----

    def _ensure_study(self):  # pragma: no cover - optional dep
        if self._study is None:
            sampler = self._optuna.samplers.TPESampler(seed=self._seed)
            self._study = self._optuna.create_study(
                direction="maximize" if self.mode == "max" else "minimize",
                sampler=sampler)
        return self._study

    def _ask(self):  # pragma: no cover - optional dep
        trial = self._ensure_study().ask()
        cfg = {}
        for key, dom in self._space.items():
            if isinstance(dom, S.Float):
                cfg[key] = trial.suggest_float(
                    key, dom.lower, dom.upper,
                    log=getattr(dom, "log", False))
            elif isinstance(dom, S.Integer):
                cfg[key] = trial.suggest_int(
                    key, dom.lower, dom.upper,
                    log=getattr(dom, "log", False))
            elif isinstance(dom, S.Categorical):
                cfg[key] = trial.suggest_categorical(
                    key, list(dom.categories))
            elif isinstance(dom, S.Domain):
                cfg[key] = dom.sample(np.random.RandomState(self._seed))
            else:
                cfg[key] = dom
        return trial, cfg

    # -- Searcher API -----------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._optuna is None:
            return self._fallback.suggest(trial_id)
        trial, cfg = self._ask()  # pragma: no cover - optional dep
        self._trials[trial_id] = trial
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        if self._optuna is None:
            self._fallback.on_trial_complete(trial_id, result, error)
            return
        trial = self._trials.pop(trial_id, None)  # pragma: no cover
        if trial is None:
            return
        state = self._optuna.trial.TrialState.COMPLETE
        value = None
        if error or not result or result.get(self.metric) is None:
            state = self._optuna.trial.TrialState.FAIL
        else:
            value = float(result[self.metric])
        self._ensure_study().tell(trial, value, state=state)


class HyperOptSearch(Searcher):  # pragma: no cover - optional dep
    def __init__(self, *a, **k):
        raise ImportError(
            "hyperopt is not available in this environment; use "
            "OptunaSearch (TPE-lite fallback), TPESearch, or "
            "BasicVariantGenerator")
