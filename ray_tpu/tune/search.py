"""Searchers: BasicVariantGenerator (grid+random), Searcher plugin API,
ConcurrencyLimiter.

Reference: python/ray/tune/search/ (basic_variant.py, searcher.py,
concurrency_limiter.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import sample as S


class Searcher:
    """Plugin interface (reference: search/searcher.py Searcher)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric, mode, config) -> bool:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random sampling
    (reference: basic_variant.py)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 num_samples: int = 1, seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._rng = np.random.RandomState(seed)
        self._points = list(points_to_evaluate or [])
        self._queue: List[Dict[str, Any]] = []
        self._generated = False

    def set_space(self, space: Dict[str, Any]) -> None:
        self._space = space
        self._generated = False

    def _generate(self) -> None:
        self._queue = []
        for point in self._points:
            cfg = S.resolve(self._space, self._rng)
            cfg.update(point)
            self._queue.append(cfg)
        grid_variants = S.expand_grid(self._space)
        for _ in range(self._num_samples):
            for variant in grid_variants:
                self._queue.append(S.resolve(variant, self._rng))
        self._generated = True

    def total_trials(self) -> int:
        if not self._generated:
            self._generate()
        return len(self._queue) + self._consumed if hasattr(
            self, "_consumed") else len(self._queue)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._generated:
            self._generate()
        if not self._queue:
            return None
        return self._queue.pop(0)


class SearchGenerator(Searcher):
    """Adapts a Searcher producing one config per suggest() to a bounded
    number of samples."""

    def __init__(self, searcher: Searcher, space: Dict[str, Any],
                 num_samples: int):
        super().__init__(searcher.metric, searcher.mode)
        self._searcher = searcher
        self._space = space
        self._remaining = num_samples

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._remaining <= 0:
            return None
        cfg = self._searcher.suggest(trial_id)
        if cfg is None:
            return None
        self._remaining -= 1
        merged = S.resolve(self._space, np.random.RandomState())
        merged.update(cfg)
        return merged

    def on_trial_result(self, trial_id, result):
        self._searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._searcher.on_trial_complete(trial_id, result, error)


class ConcurrencyLimiter(Searcher):
    """Caps concurrent suggestions (reference: concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return "PENDING"  # sentinel: try again later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "PENDING":
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class HyperOptSearch(Searcher):  # pragma: no cover - optional dep
    def __init__(self, *a, **k):
        raise ImportError(
            "hyperopt is not available in this environment; use "
            "BasicVariantGenerator or implement a custom Searcher")


class OptunaSearch(Searcher):  # pragma: no cover - optional dep
    def __init__(self, *a, **k):
        raise ImportError(
            "optuna is not available in this environment; use "
            "BasicVariantGenerator or implement a custom Searcher")
