"""Trial + TuneController: the experiment run loop.

Reference: python/ray/tune/execution/tune_controller.py:68 (step :666) and
trainable/function_trainable.py. Trials run as actors; function trainables
run the user fn in a thread inside the actor and stream results back via a
polled queue; class trainables are stepped with explicit train() calls.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.util import events as _events

from .schedulers import FIFOScheduler, TrialScheduler
from .search import BasicVariantGenerator, Searcher


class Trainable:
    """Class trainable API (reference: trainable/trainable.py)."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.iteration = 0
        self.setup(config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        return False


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    trial_dir: str
    status: str = "PENDING"  # PENDING RUNNING TERMINATED ERROR
    last_result: Dict[str, Any] = field(default_factory=dict)
    results: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    restore_from: Optional[str] = None
    actor: Any = None
    pending_ref: Any = None
    iteration: int = 0

    def metric_value(self, metric: str):
        return self.last_result.get(metric)


# --------------------------------------------------------------- actors


@ray_tpu.remote
class _ClassTrainableActor:
    def __init__(self, trainable_cls, config, trial_dir):
        os.makedirs(trial_dir, exist_ok=True)
        self._trainable = trainable_cls(config)
        self._trial_dir = trial_dir

    def train(self):
        self._trainable.iteration += 1
        result = self._trainable.step() or {}
        result.setdefault("training_iteration", self._trainable.iteration)
        return result

    def save(self):
        path = os.path.join(self._trial_dir,
                            f"checkpoint_{self._trainable.iteration:06d}")
        os.makedirs(path, exist_ok=True)
        self._trainable.save_checkpoint(path)
        return path

    def restore(self, path):
        self._trainable.load_checkpoint(path)

    def stop(self):
        self._trainable.cleanup()
        return True


@ray_tpu.remote
class _FunctionTrainableActor:
    """Runs fn(config) in a thread; results stream via a drained queue.

    Reference: function_trainable.py — the RESULT queue + report() API.
    """

    def __init__(self, fn, config, trial_dir, restore_path=None):
        import queue as _q

        os.makedirs(trial_dir, exist_ok=True)
        self._queue: "_q.Queue" = _q.Queue()
        self._done = False
        self._error: Optional[str] = None
        self._trial_dir = trial_dir

        from . import session as tune_session

        ctx = tune_session.TuneSession(
            trial_dir=trial_dir, queue=self._queue,
            checkpoint=Checkpoint(restore_path) if restore_path else None)

        def run():
            tune_session.set_session(ctx)
            try:
                fn(config)
            except BaseException as e:  # noqa: BLE001
                import traceback

                self._error = f"{e}\n{traceback.format_exc()}"
            finally:
                self._done = True

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def fetch(self):
        """Drain queued results; returns (results, done, error).

        ``_done`` is read BEFORE draining: the trainable thread puts
        its results and only then sets ``_done``, so done-before-drain
        guarantees every result is already in the queue when we report
        done=True.  The reverse order had a lost-result race — drain,
        then the thread puts its final report and sets the flag, then
        we read done=True and the controller stops the trial with
        results still queued (the tier-1 tune load flake)."""
        done, error = self._done, self._error
        out = []
        while True:
            try:
                out.append(self._queue.get_nowait())
            except Exception:
                break
        return out, done, error

    def stop(self):
        return True


# ------------------------------------------------------------ controller


class TuneController:
    def __init__(self, trainable, *, param_space: Dict[str, Any],
                 searcher: Optional[Searcher] = None,
                 scheduler: Optional[TrialScheduler] = None,
                 num_samples: int = 1,
                 metric: Optional[str] = None, mode: str = "max",
                 max_concurrent_trials: Optional[int] = None,
                 stop: Optional[Dict[str, Any]] = None,
                 storage_path: Optional[str] = None,
                 name: Optional[str] = None,
                 max_failures: int = 0,
                 trial_resources: Optional[Dict[str, float]] = None,
                 checkpoint_freq: int = 0,
                 restore_state: Optional[Dict[str, Any]] = None,
                 callbacks: Optional[List] = None):
        self.callbacks = list(callbacks or [])
        self.trainable = trainable
        self._restore_state = restore_state
        self.is_function = not (isinstance(trainable, type)
                                and issubclass(trainable, Trainable))
        self.metric = metric
        self.mode = mode
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(metric, mode)
        self.searcher = searcher or BasicVariantGenerator(
            param_space, num_samples)
        if isinstance(self.searcher, BasicVariantGenerator):
            self.searcher.set_space(param_space)
        self.searcher.set_search_properties(metric, mode, param_space)
        self.stop_criteria = stop or {}
        self.max_concurrent = max_concurrent_trials or 8
        self.max_failures = max_failures
        self.trial_resources = trial_resources or {"num_cpus": 1}
        self.checkpoint_freq = checkpoint_freq
        base = storage_path or os.path.expanduser("~/ray_tpu_results")
        self.exp_name = name or f"tune_{int(time.time())}"
        self.exp_dir = os.path.join(base, self.exp_name)
        os.makedirs(self.exp_dir, exist_ok=True)
        self.trials: List[Trial] = []
        self._failures: Dict[str, int] = {}

    # -- trial lifecycle
    def _prefill_from_restore(self) -> None:
        """Recreate trials from a saved experiment_state (Tuner.restore):
        TERMINATED trials keep their results and are not re-run; others
        restart as PENDING, resuming from their last checkpoint. The
        searcher is advanced past the restored trials so deterministic
        searchers (grid/seeded random) don't regenerate them."""
        import base64

        import cloudpickle

        saved = (self._restore_state or {}).get("trials", [])
        for rec in saved:
            if "config_pkl" in rec:
                cfg = cloudpickle.loads(base64.b64decode(rec["config_pkl"]))
            else:
                continue  # legacy repr-only state: cannot reconstruct
            trial = Trial(
                trial_id=rec["trial_id"], config=cfg,
                trial_dir=os.path.join(self.exp_dir, rec["trial_id"]))
            if rec["status"] == "TERMINATED":
                trial.status = "TERMINATED"
                trial.last_result = rec.get("last_result") or {}
                trial.iteration = rec.get("iteration", 0)
                trial.checkpoint_path = rec.get("checkpoint_path")
            else:
                trial.status = "PENDING"
                trial.restore_from = rec.get("checkpoint_path")
            self.trials.append(trial)
            sug = self.searcher.suggest(trial.trial_id)
            if sug == "PENDING":
                import warnings

                warnings.warn(
                    "restore: searcher (e.g. ConcurrencyLimiter at "
                    "capacity) did not advance past a restored trial; "
                    "deterministic searchers may regenerate its config",
                    stacklevel=2)
            if trial.status == "TERMINATED":
                # free ConcurrencyLimiter-style live slots immediately:
                # restored-complete trials never reach the normal
                # completion path
                try:
                    self.searcher.on_trial_complete(
                        trial.trial_id,
                        result=trial.last_result or None)
                except Exception:
                    pass

    def _new_trial(self) -> Optional[Trial]:
        trial_id = uuid.uuid4().hex[:8]
        cfg = self.searcher.suggest(trial_id)
        if cfg is None:
            return None
        if cfg == "PENDING":
            return "PENDING"
        trial = Trial(trial_id=trial_id, config=cfg,
                      trial_dir=os.path.join(self.exp_dir, trial_id))
        self.trials.append(trial)
        return trial

    def _start_trial(self, trial: Trial) -> None:
        opts = dict(self.trial_resources)
        if self.is_function:
            trial.actor = _FunctionTrainableActor.options(**opts).remote(
                self.trainable, trial.config, trial.trial_dir,
                trial.restore_from)
            trial.pending_ref = trial.actor.fetch.remote()
        else:
            trial.actor = _ClassTrainableActor.options(**opts).remote(
                self.trainable, trial.config, trial.trial_dir)
            if trial.restore_from:
                ray_tpu.get(trial.actor.restore.remote(trial.restore_from))
            trial.pending_ref = trial.actor.train.remote()
        trial.restore_from = None
        trial.status = "RUNNING"
        _events.emit("INFO", _events.SOURCE_TUNE,
                     f"trial {trial.trial_id} -> RUNNING "
                     f"(experiment {self.exp_name})",
                     entity_id=trial.trial_id, state="RUNNING",
                     experiment=self.exp_name)
        for cb in self.callbacks:
            cb.on_trial_start(trial.iteration, self.trials, trial)

    def _stop_trial(self, trial: Trial, status: str = "TERMINATED") -> None:
        trial.status = status
        _events.emit("ERROR" if status == "ERROR" else "INFO",
                     _events.SOURCE_TUNE,
                     f"trial {trial.trial_id} -> {status} "
                     f"(experiment {self.exp_name})",
                     entity_id=trial.trial_id, state=status,
                     experiment=self.exp_name,
                     iteration=trial.last_result.get(
                         "training_iteration", 0))
        if trial.actor is not None:
            try:
                if not self.is_function and status == "TERMINATED":
                    ray_tpu.get(trial.actor.stop.remote(), timeout=5)
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        trial.pending_ref = None
        for cb in self.callbacks:
            if status == "TERMINATED":
                cb.on_trial_complete(trial.iteration, self.trials, trial)
            elif status == "ERROR":
                cb.on_trial_error(trial.iteration, self.trials, trial)

    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    # -- PBT exploit
    def exploit_trial(self, trial: Trial, donor: Trial,
                      new_config: Dict[str, Any]) -> None:
        ckpt = donor.checkpoint_path
        if ckpt is None and not self.is_function and donor.actor is not None:
            try:
                ckpt = ray_tpu.get(donor.actor.save.remote(), timeout=30)
                donor.checkpoint_path = ckpt
            except Exception:
                return
        if ckpt is None:
            return
        self._stop_trial(trial, status="PENDING")
        trial.config = new_config
        trial.restore_from = ckpt
        trial.iteration = trial.last_result.get("training_iteration", 0)
        self._start_trial(trial)

    # -- stopping criteria
    def _should_stop(self, trial: Trial, result: Dict[str, Any]) -> bool:
        for key, bound in self.stop_criteria.items():
            v = result.get(key)
            if v is not None and v >= bound:
                return True
        return False

    def _handle_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        result.setdefault("trial_id", trial.trial_id)
        result.setdefault("config", trial.config)
        result.setdefault(
            "training_iteration",
            trial.last_result.get("training_iteration", 0) + 1)
        trial.last_result = result
        trial.results.append(result)
        ckpt = result.pop("_checkpoint", None)
        if ckpt:
            trial.checkpoint_path = ckpt
        it = result.get("training_iteration", 0)
        for cb in self.callbacks:
            cb.on_trial_result(it, self.trials, trial, result)
            if ckpt:
                cb.on_checkpoint(it, self.trials, trial, ckpt)
        self.searcher.on_trial_result(trial.trial_id, result)
        decision = self.scheduler.on_trial_result(self, trial, result)
        if self._should_stop(trial, result):
            decision = TrialScheduler.STOP
        return decision

    def _handle_error(self, trial: Trial, err: str) -> None:
        n = self._failures.get(trial.trial_id, 0)
        if n < self.max_failures or self.max_failures < 0:
            self._failures[trial.trial_id] = n + 1
            _events.emit("WARNING", _events.SOURCE_TUNE,
                         f"trial {trial.trial_id} failed "
                         f"(attempt {n + 1}), retrying from checkpoint",
                         entity_id=trial.trial_id, attempt=n + 1)
            self._stop_trial(trial, status="PENDING")
            trial.restore_from = trial.checkpoint_path
            self._start_trial(trial)
        else:
            trial.error = err
            self._stop_trial(trial, status="ERROR")
            self.searcher.on_trial_complete(trial.trial_id, error=True)

    # -- checkpointing of experiment state
    @staticmethod
    def _config_pkl(t: Trial) -> str:
        """Lossless config for Tuner.restore, cached per config object —
        save_experiment_state runs every loop iteration and configs only
        change on PBT exploit."""
        import base64

        import cloudpickle

        cached = getattr(t, "_config_pkl_cache", None)
        if cached is None or cached[0] is not t.config:
            cached = (t.config, base64.b64encode(
                cloudpickle.dumps(t.config)).decode())
            t._config_pkl_cache = cached
        return cached[1]

    def save_experiment_state(self) -> None:
        state = {
            "exp_name": self.exp_name,
            "trials": [{
                "trial_id": t.trial_id, "config_repr": repr(t.config),
                "config_pkl": self._config_pkl(t),
                "status": t.status, "last_result": _json_safe(t.last_result),
                "checkpoint_path": t.checkpoint_path, "error": t.error,
                "iteration": t.iteration,
            } for t in self.trials],
        }
        with open(os.path.join(self.exp_dir, "experiment_state.json"),
                  "w") as f:
            json.dump(state, f, indent=2, default=str)

    # -- the run loop (reference: tune_controller.py step :666)
    def run(self) -> List[Trial]:
        if self._restore_state:
            self._prefill_from_restore()
        searcher_exhausted = False
        while True:
            # launch new trials
            running = [t for t in self.trials if t.status == "RUNNING"]
            while (not searcher_exhausted
                   and len(running) < self.max_concurrent):
                t = self._new_trial()
                if t is None:
                    searcher_exhausted = True
                    break
                if t == "PENDING":
                    break
                self._start_trial(t)
                running.append(t)
            # restart pending (exploited / retried / restored) trials
            for t in self.trials:
                if t.status == "PENDING" and t.actor is None \
                        and len([x for x in self.trials
                                 if x.status == "RUNNING"]) < self.max_concurrent:
                    self._start_trial(t)

            running = [t for t in self.trials if t.status == "RUNNING"]
            if not running:
                if searcher_exhausted:
                    break
                time.sleep(0.01)
                continue

            refs = {t.pending_ref: t for t in running if t.pending_ref}
            ready, _ = ray_tpu.wait(list(refs.keys()),
                                    num_returns=1, timeout=1.0)
            for ref in ready:
                trial = refs[ref]
                try:
                    payload = ray_tpu.get(ref)
                except Exception as e:  # actor/task failure
                    self._handle_error(trial, str(e))
                    continue
                if self.is_function:
                    results, done, error = payload
                    decision = TrialScheduler.CONTINUE
                    for r in results:
                        decision = self._handle_result(trial, r)
                        if decision == TrialScheduler.STOP:
                            break
                    if error:
                        self._handle_error(trial, error)
                    elif done or decision == TrialScheduler.STOP:
                        self._stop_trial(trial)
                        self.searcher.on_trial_complete(
                            trial.trial_id, trial.last_result)
                        self.scheduler.on_trial_complete(
                            self, trial, trial.last_result)
                    else:
                        time.sleep(0.01)
                        trial.pending_ref = trial.actor.fetch.remote()
                else:
                    decision = self._handle_result(trial, payload)
                    it = trial.last_result.get("training_iteration", 0)
                    if self.checkpoint_freq and it % self.checkpoint_freq \
                            == 0 and trial.actor is not None:
                        try:
                            trial.checkpoint_path = ray_tpu.get(
                                trial.actor.save.remote(), timeout=30)
                        except Exception:
                            pass
                    if decision == TrialScheduler.STOP:
                        if trial.actor is not None:
                            try:
                                trial.checkpoint_path = ray_tpu.get(
                                    trial.actor.save.remote(), timeout=30)
                            except Exception:
                                pass
                        self._stop_trial(trial)
                        self.searcher.on_trial_complete(
                            trial.trial_id, trial.last_result)
                        self.scheduler.on_trial_complete(
                            self, trial, trial.last_result)
                    elif trial.status == "RUNNING":
                        trial.pending_ref = trial.actor.train.remote()
            self.save_experiment_state()
        self.save_experiment_state()
        for cb in self.callbacks:
            cb.on_experiment_end(self.trials)
        return self.trials


def _json_safe(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out
