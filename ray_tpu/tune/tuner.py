"""Tuner / tune.run / ResultGrid.

Reference: python/ray/tune/tuner.py:44, tune.py:267,
result_grid.py, analysis/experiment_analysis.py.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig

from .controller import Trainable, Trial, TuneController
from .schedulers import TrialScheduler
from .search import BasicVariantGenerator, Searcher


@dataclass
class TuneConfig:
    """Reference: python/ray/tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    trial_resources: Optional[Dict[str, float]] = None
    checkpoint_freq: int = 0


@dataclass
class TuneResult:
    metrics: Dict[str, Any]
    config: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[str]
    path: str

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame([self.metrics])


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i: int) -> TuneResult:
        return self._to_result(self._trials[i])

    def _to_result(self, t: Trial) -> TuneResult:
        return TuneResult(
            metrics=t.last_result, config=t.config,
            checkpoint=Checkpoint(t.checkpoint_path)
            if t.checkpoint_path else None,
            error=t.error, path=t.trial_dir)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TuneResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set in TuneConfig or here)")
        ok = [t for t in self._trials
              if t.last_result.get(metric) is not None]
        if not ok:
            raise RuntimeError("no trial reported the metric "
                               f"{metric!r}")
        best = (max if mode == "max" else min)(
            ok, key=lambda t: t.last_result[metric])
        return self._to_result(best)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = dict(t.last_result)
            for k, v in t.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Union[Callable, type, "Any"], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._stop = getattr(self._run_config, "stop", None)

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        rc = self._run_config
        trainable = self._trainable
        param_space = dict(self._param_space)

        # A Train trainer instance (e.g. JaxTrainer) runs as a single-trial
        # experiment whose function re-instantiates the trainer per config
        # (reference: BaseTrainer.fit wraps as a Tune Trainable :697).
        from ray_tpu.train.trainer import JaxTrainer

        if isinstance(trainable, JaxTrainer):
            trainable = _make_trainer_fn(trainable)

        searcher = tc.search_alg
        if searcher is not None and hasattr(searcher, "set_space"):
            searcher.set_space(param_space)
        if searcher is not None and not isinstance(
                searcher, BasicVariantGenerator) and tc.num_samples:
            # model-based searchers suggest forever: bound the run by
            # TuneConfig.num_samples (reference: SearchGenerator wrapping
            # in tune.run)
            from .search import SearchGenerator

            searcher = SearchGenerator(searcher, param_space,
                                       tc.num_samples)
        restore_path = getattr(self, "_restore_path", None)
        if restore_path:
            # continue in the SAME experiment dir so trial dirs/checkpoints
            # of restored trials resolve
            rc.storage_path = os.path.dirname(os.path.abspath(restore_path))
            rc.name = os.path.basename(os.path.abspath(restore_path))
        controller = TuneController(
            trainable,
            param_space=param_space,
            searcher=searcher,
            scheduler=tc.scheduler,
            num_samples=tc.num_samples,
            metric=tc.metric, mode=tc.mode,
            max_concurrent_trials=tc.max_concurrent_trials,
            stop=self._stop,
            storage_path=rc.storage_path,
            name=rc.name,
            max_failures=rc.failure_config.max_failures,
            trial_resources=tc.trial_resources,
            checkpoint_freq=tc.checkpoint_freq,
            restore_state=getattr(self, "_restore_state", None),
            callbacks=rc.callbacks,
        )
        trials = controller.run()
        errored = [t for t in trials if t.error]
        if trials and len(errored) == len(trials):
            # every trial failed: returning a normal-looking ResultGrid
            # buries the errors behind private state (the round-5 stain —
            # 25/25 silently ERRORed). Raise with the first traceback so
            # the failure is visible at the call site (reference:
            # tune.run(raise_on_failed_trial=True) default).
            raise RuntimeError(
                f"all {len(trials)} trial(s) errored; first error:\n"
                f"{errored[0].error}")
        if errored:
            import warnings

            warnings.warn(
                f"{len(errored)}/{len(trials)} trial(s) errored; see "
                "ResultGrid.errors / result.error for tracebacks",
                RuntimeWarning, stacklevel=2)
        return ResultGrid(trials, tc.metric, tc.mode)

    @classmethod
    def restore(cls, path: str, trainable, *, param_space=None,
                tune_config=None, run_config=None) -> "Tuner":
        """Resume an interrupted experiment from its state file.

        Pass the ORIGINAL ``param_space``/``tune_config`` so trials not yet
        generated before the interruption are still produced; restored
        trials consume the first suggestions (deterministic searchers —
        grid, seeded random — realign; finished trials are not re-run).
        """
        state_file = os.path.join(path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        t = cls(trainable, param_space=param_space, tune_config=tune_config,
                run_config=run_config)
        t._restore_path = path
        t._restore_state = state
        return t


def _make_trainer_fn(trainer):
    base_loop = trainer.train_loop
    base_config = dict(trainer.config or {})
    scaling = trainer.scaling
    datasets = trainer.datasets

    def trainer_fn(config):
        from ray_tpu.train.trainer import JaxTrainer

        merged = dict(base_config)
        merged.update(config)
        t = JaxTrainer(base_loop, train_loop_config=merged,
                       scaling_config=scaling, datasets=datasets)
        result = t.fit()
        # surface final metrics to Tune
        from . import session as tune_session

        if result.metrics:
            tune_session.report(result.metrics)

    return trainer_fn


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler: Optional[TrialScheduler] = None,
        search_alg: Optional[Searcher] = None,
        stop: Optional[Dict[str, Any]] = None,
        storage_path: Optional[str] = None, name: Optional[str] = None,
        max_concurrent_trials: Optional[int] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        checkpoint_freq: int = 0,
        **_ignored) -> ResultGrid:
    """Functional entry point (reference: tune.py:267 tune.run)."""
    controller = TuneController(
        trainable, param_space=config or {}, searcher=search_alg,
        scheduler=scheduler, num_samples=num_samples, metric=metric,
        mode=mode, max_concurrent_trials=max_concurrent_trials, stop=stop,
        storage_path=storage_path, name=name,
        trial_resources=resources_per_trial,
        checkpoint_freq=checkpoint_freq)
    trials = controller.run()
    return ResultGrid(trials, metric, mode)


def with_parameters(fn, **params):
    """Bind large params via the object store
    (reference: tune/trainable/util.py with_parameters)."""
    import ray_tpu

    refs = {k: ray_tpu.put(v) for k, v in params.items()}

    def wrapped(config):
        import ray_tpu as _rt

        resolved = {k: _rt.get(r) for k, r in refs.items()}
        return fn(config, **resolved)

    wrapped.__name__ = getattr(fn, "__name__", "wrapped")
    return wrapped


def with_resources(fn, resources: Dict[str, float]):
    fn.__tune_resources__ = resources
    return fn
