"""ray_tpu.tune — hyperparameter optimisation engine.

Reference: python/ray/tune/ (Tuner, TuneController, searchers, schedulers).
"""

from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("tune")
del _rlu


from ray_tpu.tune.controller import Trainable, Trial, TuneController  # noqa: F401
from ray_tpu.tune.sample import (  # noqa: F401
    choice,
    grid_search,
    lograndint,
    loguniform,
    qloguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.callbacks import (  # noqa: F401
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    ConcurrencyLimiter,
    OptunaSearch,
    Searcher,
    TPESearch,
)
from ray_tpu.tune.session import get_checkpoint, get_trial_dir, report  # noqa: F401
from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid,
    TuneConfig,
    Tuner,
    run,
    with_parameters,
    with_resources,
)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "run", "Trainable", "Trial",
    "TuneController", "report", "get_checkpoint", "get_trial_dir",
    "uniform", "quniform", "loguniform", "qloguniform", "randint",
    "qrandint", "lograndint", "randn", "choice", "sample_from",
    "grid_search", "Searcher", "BasicVariantGenerator",
    "ConcurrencyLimiter", "OptunaSearch", "TPESearch",
    "TrialScheduler", "FIFOScheduler",
    "AsyncHyperBandScheduler", "HyperBandScheduler", "MedianStoppingRule",
    "PopulationBasedTraining", "PB2", "Callback", "JsonLoggerCallback",
    "CSVLoggerCallback", "with_parameters", "with_resources",
]
