"""Per-trial session for function trainables: tune.report / get_checkpoint.

Reference: ray.tune's use of the shared train/tune session
(python/ray/air/_internal/session.py).
"""

from __future__ import annotations

import os
import shutil
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class TuneSession:
    trial_dir: str
    queue: Any
    checkpoint: Optional[Checkpoint] = None


_session: Optional[TuneSession] = None


def set_session(s: Optional[TuneSession]) -> None:
    global _session
    _session = s


def get_session() -> Optional[TuneSession]:
    return _session


def report(metrics: Optional[Dict[str, Any]] = None,
           checkpoint: Optional[Checkpoint] = None,
           **kwargs: Any) -> None:
    """Report metrics (+ optional checkpoint) from inside a trial fn.

    Accepts both styles the reference supports: the new dict form
    ``tune.report({"loss": x})`` and the legacy kwargs form
    ``tune.report(loss=x)`` (mixing merges, kwargs win).
    """
    merged: Dict[str, Any] = dict(metrics or {})
    merged.update(kwargs)
    metrics = merged
    s = _session
    if s is None:
        # Fall back to the Train session (JaxTrainer inside Tune)
        from ray_tpu.train import session as train_session

        train_session.report(metrics, checkpoint=checkpoint)
        return
    result = dict(metrics)
    if checkpoint is not None:
        # persist into the trial dir so it outlives the actor
        dest = os.path.join(s.trial_dir,
                            f"checkpoint_{uuid.uuid4().hex[:6]}")
        shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        result["_checkpoint"] = dest
    result.setdefault("timestamp", time.time())
    s.queue.put(result)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _session
    if s is None:
        from ray_tpu.train import session as train_session

        return train_session.get_checkpoint()
    return s.checkpoint


def get_trial_dir() -> Optional[str]:
    return _session.trial_dir if _session else None
