"""Search-space DSL: tune.uniform/loguniform/choice/grid_search/...

Reference: python/ray/tune/search/sample.py (Domain classes) and
variant_generator grid expansion.
"""

from __future__ import annotations

import numpy as np
from typing import Any, Callable, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: np.random.RandomState) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.lower),
                                         np.log(self.upper))))
        else:
            v = float(rng.uniform(self.lower, self.upper))
        if self.q:
            v = float(np.round(v / self.q) * self.q)
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False,
                 q: int = 1):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = int(np.exp(rng.uniform(np.log(self.lower),
                                       np.log(self.upper))))
        else:
            v = int(rng.randint(self.lower, self.upper))
        if self.q > 1:
            v = int(np.round(v / self.q) * self.q)
        return max(self.lower, min(v, self.upper - 1))


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[rng.randint(len(self.categories))]


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        try:
            return self.fn({})
        except TypeError:
            return self.fn()


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda _=None: float(np.random.randn() * sd + mean))


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-product over grid_search entries; other values pass through."""
    grids: List[tuple] = []

    def walk(prefix, node):
        if isinstance(node, dict) and not _is_grid(node):
            for k, v in node.items():
                walk(prefix + (k,), v)
        elif _is_grid(node):
            grids.append((prefix, node["grid_search"]))

    walk((), space)
    if not grids:
        return [space]
    import itertools

    combos = itertools.product(*(vals for _, vals in grids))
    out = []
    for combo in combos:
        import copy

        cfg = copy.deepcopy(space)
        for (path, _), val in zip(grids, combo):
            d = cfg
            for p in path[:-1]:
                d = d[p]
            d[path[-1]] = val
        out.append(cfg)
    return out


def resolve(space: Dict[str, Any], rng: np.random.RandomState
            ) -> Dict[str, Any]:
    """Sample every Domain in (a grid-expanded) config."""

    def walk(node):
        if isinstance(node, Domain):
            return node.sample(rng)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(space)
