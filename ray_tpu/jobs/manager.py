"""JobManager (head-side) + JobSubmissionClient (REST client).

Reference: python/ray/dashboard/modules/job/job_manager.py (submit_job →
driver subprocess with RAY_ADDRESS env; status polling via actor),
common.py (JobStatus/JobInfo), sdk.py (JobSubmissionClient over HTTP).

The driver subprocess here connects back through the head's ClientServer
(core/client_server.py) via ``RAY_TPU_ADDRESS``/``RAY_TPU_CLUSTER_KEY`` —
a real shared-cluster driver, not a fresh local cluster. Logs stream to a
per-job file; stop sends SIGTERM then SIGKILL to the process group.
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import tempfile
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    driver_exit_code: Optional[int] = None
    stop_requested: bool = False

    def to_dict(self) -> dict:
        return asdict(self)


class JobManager:
    """Spawns and tracks job driver subprocesses."""

    def __init__(self, client_address=None, cluster_key_hex: Optional[str] = None,
                 log_dir: Optional[str] = None):
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._client_address = client_address
        self._cluster_key_hex = cluster_key_hex
        self._log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "ray_tpu_jobs")
        os.makedirs(self._log_dir, exist_ok=True)

    # ---- API --------------------------------------------------------------
    def submit_job(self, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None) -> str:
        submission_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if submission_id in self._jobs:
                raise ValueError(f"job {submission_id} already exists")
            info = JobInfo(submission_id=submission_id,
                           entrypoint=entrypoint,
                           metadata=dict(metadata or {}),
                           runtime_env=dict(runtime_env or {}),
                           start_time=time.time())
            self._jobs[submission_id] = info

        env = dict(os.environ)
        renv = runtime_env or {}
        env.update({str(k): str(v)
                    for k, v in (renv.get("env_vars") or {}).items()})
        if self._client_address is not None:
            host, port = self._client_address
            env["RAY_TPU_ADDRESS"] = f"ray_tpu://{host}:{port}"
        if self._cluster_key_hex:
            env["RAY_TPU_CLUSTER_KEY"] = self._cluster_key_hex
        env["RAY_TPU_JOB_SUBMISSION_ID"] = submission_id
        cwd = renv.get("working_dir") or os.getcwd()

        log_path = self.log_path(submission_id)
        logf = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, cwd=cwd, env=env,
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True)  # own pgid: stop kills the tree
        except OSError as e:
            info.status = JobStatus.FAILED
            info.message = f"failed to spawn: {e}"
            info.end_time = time.time()
            logf.close()
            return submission_id
        finally:
            logf.close()
        with self._lock:
            info.status = JobStatus.RUNNING
            info.message = "driver running"
            self._procs[submission_id] = proc
        threading.Thread(target=self._monitor,
                         args=(submission_id, proc),
                         name=f"job-{submission_id}", daemon=True).start()
        return submission_id

    def _monitor(self, submission_id: str, proc: subprocess.Popen) -> None:
        rc = proc.wait()
        with self._lock:
            info = self._jobs[submission_id]
            self._procs.pop(submission_id, None)
            # the monitor is the single writer of terminal status: a clean
            # exit-0 that raced an (undelivered) stop is SUCCEEDED, not
            # STOPPED
            if rc == 0:
                info.status = JobStatus.SUCCEEDED
                info.message = "driver exited 0"
            elif info.stop_requested:
                info.status = JobStatus.STOPPED
                info.message = "stopped by user"
            else:
                info.status = JobStatus.FAILED
                info.message = f"driver exited {rc}"
            info.driver_exit_code = rc
            info.end_time = time.time()

    def stop_job(self, submission_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(submission_id)
            proc = self._procs.get(submission_id)
            if info is None:
                raise KeyError(submission_id)
            if proc is None or proc.poll() is not None:
                return False  # already terminal; _monitor records the truth
            info.stop_requested = True
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return False
        deadline = time.time() + 3.0
        while time.time() < deadline:
            if proc.poll() is not None:
                return True
            time.sleep(0.05)
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def delete_job(self, submission_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(submission_id)
            if info is None:
                raise KeyError(submission_id)
            if info.status not in JobStatus.TERMINAL:
                raise RuntimeError(
                    f"job {submission_id} is {info.status}; stop it first")
            del self._jobs[submission_id]
        try:
            os.remove(self.log_path(submission_id))
        except OSError:
            pass
        return True

    def get_job_status(self, submission_id: str) -> str:
        with self._lock:
            info = self._jobs.get(submission_id)
        if info is None:
            raise KeyError(submission_id)
        return info.status

    def get_job_info(self, submission_id: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(submission_id)
        if info is None:
            raise KeyError(submission_id)
        return info

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def log_path(self, submission_id: str) -> str:
        return os.path.join(self._log_dir, f"{submission_id}.log")

    def read_job_logs(self, submission_id: str, offset: int = 0):
        """(text, next_byte_offset) from byte ``offset``. Tailers must
        carry ``next_byte_offset`` (not len(text): decoding with
        errors='replace' changes lengths for non-UTF-8 / torn multibyte
        tails, which would desynchronize a re-encoded offset)."""
        with self._lock:
            if submission_id not in self._jobs:
                raise KeyError(submission_id)
        try:
            with open(self.log_path(submission_id), "rb") as f:
                if offset:
                    f.seek(offset)
                data = f.read()
                return data.decode(errors="replace"), offset + len(data)
        except OSError:
            return "", offset

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        return self.read_job_logs(submission_id, offset)[0]

    def shutdown(self) -> None:
        with self._lock:
            procs = list(self._procs.items())
        for sid, proc in procs:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except Exception:
                pass


# --------------------------------------------------------------------------- #
# REST client (reference: dashboard/modules/job/sdk.py JobSubmissionClient)
# --------------------------------------------------------------------------- #


class JobSubmissionClient:
    """HTTP client against the dashboard's /api/jobs endpoints.

    ``auth_token`` (or env ``RAY_TPU_JOB_TOKEN``) is required when the
    dashboard was started on a non-loopback interface."""

    def __init__(self, address: str, auth_token: Optional[str] = None):
        self._base = address.rstrip("/")
        if not self._base.startswith("http"):
            self._base = "http://" + self._base
        self._token = auth_token or os.environ.get("RAY_TPU_JOB_TOKEN", "")

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Any:
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        req = urllib.request.Request(
            self._base + path, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                raw = resp.read().decode()
                ctype = resp.headers.get_content_type()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(
                f"{method} {path} -> {e.code}: {detail}") from None
        return json.loads(raw) if ctype == "application/json" else raw

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None,
                   submission_id: Optional[str] = None) -> str:
        out = self._request("POST", "/api/jobs/", {
            "entrypoint": entrypoint, "runtime_env": runtime_env,
            "metadata": metadata, "submission_id": submission_id,
        })
        return out["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}")["status"]

    def get_job_info(self, submission_id: str) -> dict:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def list_jobs(self) -> List[dict]:
        return self._request("GET", "/api/jobs/")

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        return self._get_logs(submission_id, offset)[0]

    def _get_logs(self, submission_id: str, offset: int = 0):
        """(text, next_byte_offset) — offset from the X-Next-Offset header
        so polling stays byte-accurate across encodings."""
        import urllib.error
        import urllib.request

        path = f"/api/jobs/{submission_id}/logs"
        if offset:
            path += f"?offset={offset}"
        req = urllib.request.Request(self._base + path)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                text = resp.read().decode(errors="replace")
                nxt = int(resp.headers.get("X-Next-Offset") or offset)
        except urllib.error.HTTPError as e:
            raise RuntimeError(
                f"GET {path} -> {e.code}: "
                f"{e.read().decode(errors='replace')}") from None
        return text, nxt

    def stop_job(self, submission_id: str) -> bool:
        return self._request(
            "POST", f"/api/jobs/{submission_id}/stop")["stopped"]

    def delete_job(self, submission_id: str) -> bool:
        return self._request(
            "DELETE", f"/api/jobs/{submission_id}")["deleted"]

    def tail_job_logs(self, submission_id: str, interval: float = 0.5):
        """Generator yielding log increments until the job terminates.
        Polls with a byte offset so each request transfers only new text."""
        seen = 0
        while True:
            chunk, seen = self._get_logs(submission_id, offset=seen)
            if chunk:
                yield chunk
            if self.get_job_status(submission_id) in JobStatus.TERMINAL:
                rest, seen = self._get_logs(submission_id, offset=seen)
                if rest:
                    yield rest
                return
            time.sleep(interval)
