"""Job submission: drive entrypoint scripts against a running cluster.

Reference: python/ray/dashboard/modules/job/ — ``JobManager`` spawns the
entrypoint as a child process whose driver connects to the existing
cluster; ``JobSubmissionClient`` is the REST client
(python/ray/dashboard/modules/job/sdk.py). Same split here: the manager
(jobs/manager.py) execs entrypoints with ``RAY_TPU_ADDRESS`` pointing at
the head's client server, and the REST surface lives on the dashboard
HTTP server (dashboard/__init__.py, /api/jobs/*).
"""

from ray_tpu.jobs.manager import (  # noqa: F401
    JobInfo,
    JobManager,
    JobStatus,
    JobSubmissionClient,
)

__all__ = ["JobManager", "JobStatus", "JobInfo", "JobSubmissionClient"]
